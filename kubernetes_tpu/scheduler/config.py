"""The versioned scheduler configuration API.

Reference: KubeSchedulerConfiguration (apis/config/types.go:37-100) —
profiles with per-plugin weights/enablement, backoff bounds, parallelism
and percentageOfNodesToScore — with defaulting and validation
(apis/config/{v1,validation}).  Mapped onto the TPU design:

  * score-plugin weights/disables become the profile's ScoreConfig (a
    disabled score plugin is weight 0 — kernels read weights directly);
  * FILTER plugins cannot be individually disabled: the filter chain is
    one fused kernel, and validation rejects the attempt rather than
    silently ignoring it;
  * parallelism (goroutine fan-out, types.go:48) and
    percentageOfNodesToScore (adaptive sampling) have no TPU meaning —
    one dispatch filters and scores every node (SURVEY §2.7).  They are
    accepted for config-file parity and validated, nothing more.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional, Tuple

from ..ops.schema import SnapshotLimits
from ..ops.scores import DEFAULT_SCORE_CONFIG, ScoreConfig

# Score plugins that map onto ScoreConfig weights (names/names.go:20-43).
SCORE_PLUGIN_WEIGHTS = {
    "NodeResourcesFit": "fit_weight",
    "NodeResourcesBalancedAllocation": "balanced_weight",
    "NodeAffinity": "node_affinity_weight",
    "TaintToleration": "taint_weight",
    "PodTopologySpread": "spread_weight",
    "InterPodAffinity": "interpod_weight",
    "ImageLocality": "image_weight",
}


@dataclass
class ProfileConfig:
    """One scheduler profile (apis/config KubeSchedulerProfile)."""

    scheduler_name: str = "default-scheduler"
    score_config: ScoreConfig = field(default_factory=lambda: DEFAULT_SCORE_CONFIG)
    disabled_score_plugins: Tuple[str, ...] = ()

    def effective_score_config(self) -> ScoreConfig:
        cfg = self.score_config
        for name in self.disabled_score_plugins:
            cfg = replace(cfg, **{SCORE_PLUGIN_WEIGHTS[name]: 0.0})
        return cfg


@dataclass
class SchedulerConfiguration:
    profiles: List[ProfileConfig] = field(
        default_factory=lambda: [ProfileConfig()]
    )
    batch_size: int = 4096
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    assume_ttl_seconds: float = 30.0
    unschedulable_flush_seconds: float = 300.0
    max_preemptions_per_cycle: int = 16
    # parity-only knobs (see module docstring)
    parallelism: int = 16
    percentage_of_nodes_to_score: int = 100
    limits: Optional[SnapshotLimits] = None

    def validate(self) -> "SchedulerConfiguration":
        """Raise ValueError on an invalid configuration (the
        apis/config/validation analogue); returns self for chaining."""
        if not self.profiles:
            raise ValueError("at least one profile is required")
        names = [p.scheduler_name for p in self.profiles]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate profile schedulerName in {names}")
        for p in self.profiles:
            for plugin in p.disabled_score_plugins:
                if plugin not in SCORE_PLUGIN_WEIGHTS:
                    raise ValueError(
                        f"unknown or non-disableable score plugin {plugin!r} "
                        f"(filter plugins are fused; known: "
                        f"{sorted(SCORE_PLUGIN_WEIGHTS)})"
                    )
            cfg = p.score_config
            for f_name in (
                "fit_weight", "balanced_weight", "node_affinity_weight",
                "taint_weight", "spread_weight", "interpod_weight",
                "image_weight",
            ):
                if getattr(cfg, f_name) < 0:
                    raise ValueError(f"{p.scheduler_name}: {f_name} < 0")
            shape = cfg.rtcr_shape
            if not shape or any(
                b[0] <= a[0] for a, b in zip(shape, shape[1:])
            ):
                raise ValueError(
                    f"{p.scheduler_name}: rtcr_shape utilization points "
                    "must be non-empty and strictly increasing "
                    "(apis/config/validation's shape check)"
                )
            if cfg.fit_strategy not in (
                "LeastAllocated", "MostAllocated", "RequestedToCapacityRatio"
            ):
                raise ValueError(
                    f"{p.scheduler_name}: unknown fit_strategy "
                    f"{cfg.fit_strategy!r}"
                )
        if self.batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if self.pod_initial_backoff_seconds <= 0:
            raise ValueError("pod_initial_backoff_seconds must be positive")
        if self.pod_max_backoff_seconds < self.pod_initial_backoff_seconds:
            raise ValueError(
                "pod_max_backoff_seconds < pod_initial_backoff_seconds"
            )
        if not (0 <= self.percentage_of_nodes_to_score <= 100):
            raise ValueError("percentage_of_nodes_to_score must be 0..100")
        if self.max_preemptions_per_cycle < 0:
            raise ValueError("max_preemptions_per_cycle must be >= 0")
        return self
