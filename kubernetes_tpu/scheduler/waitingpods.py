"""The waiting-pods map — Permit's asynchronous half.

Reference: pkg/scheduler/framework/runtime/waiting_pods_map.go + the
Permit extension point (framework/interface.go:330-666): a Permit
plugin may return Wait with a timeout; the pod parks in the waiting map
while its binding goroutine blocks in WaitOnPermit
(schedule_one.go:278).  Any plugin may later Allow or Reject it; the
timeout rejects.  This is the extension point real coscheduling
plugins are built on (scheduler/coscheduling.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..api import types as api
from .queue import pod_key


class WaitingPod:
    """One pod parked at Permit (waitingPod, waiting_pods_map.go:52).

    Decisions LATCH: the first of allow/reject/timeout wins and later
    calls report whether they prevailed — the reference's
    compare-and-swap on the waiting pod's status.  try_claim/allow/
    release_claim give group releasers (coscheduling) a two-phase
    commit: claim every member atomically, then finalize — so a member
    timing out mid-release can never yield a partially-allowed gang."""

    def __init__(self, pod: api.Pod, node: str, timeout: float):
        self.pod = pod
        self.node = node
        self.deadline = time.monotonic() + timeout
        self._done = threading.Event()
        self._mu = threading.Lock()
        self._claimed = False           # guarded_by: _mu
        self._verdict: Optional[str] = None  # "allow" | reason  # guarded_by: _mu

    def try_claim(self) -> bool:
        """Atomically reserve the decision (phase 1 of a group release);
        False when already decided or claimed."""
        with self._mu:
            if self._verdict is not None or self._claimed:
                return False
            self._claimed = True
            return True

    def release_claim(self) -> None:
        """Abort phase 1 — the pod returns to plain waiting."""
        with self._mu:
            self._claimed = False

    def allow(self) -> bool:
        """Finalize allow; True iff the pod ends allowed."""
        with self._mu:
            if self._verdict is None:
                self._verdict = "allow"
                self._claimed = False
                self._done.set()
            return self._verdict == "allow"

    def reject(self, reason: str = "rejected") -> bool:
        """Latch a rejection; False when already decided or a group
        release holds the claim (the claimer's decision wins)."""
        with self._mu:
            if self._claimed:
                return False
            if self._verdict is None:
                self._verdict = reason
                self._done.set()
            return self._verdict == reason

    def _locked_verdict(self) -> Optional[str]:
        """The latched decision, read under the mutex: wait()'s readers
        run on the binding thread while allow/reject latch from plugin
        threads — the unlocked read was a graftlint guarded-by finding."""
        with self._mu:
            return self._verdict

    def wait(self) -> str:
        """Block until Allow/Reject/timeout (WaitOnPermit); returns
        "allow" or the rejection reason ("timeout" when the permit
        window lapsed).  A timeout racing an in-flight group claim
        defers to the claimer's decision."""
        while True:
            remaining = self.deadline - time.monotonic()
            if self._done.wait(timeout=max(remaining, 0)):
                return self._locked_verdict() or "rejected"
            if self.reject("timeout"):
                return "timeout"
            # claimed: the group release is deciding — wait it out
            if self._done.wait(timeout=0.05):
                return self._locked_verdict() or "rejected"


class WaitingPodsMap:
    GUARDED_FIELDS = {"_pods": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._pods: Dict[str, WaitingPod] = {}

    def add(self, wp: WaitingPod) -> None:
        with self._lock:
            self._pods[pod_key(wp.pod)] = wp

    def remove(self, pod: api.Pod) -> None:
        with self._lock:
            self._pods.pop(pod_key(pod), None)

    def get(self, pod: api.Pod) -> Optional[WaitingPod]:
        with self._lock:
            return self._pods.get(pod_key(pod))

    def iterate(self) -> List[WaitingPod]:
        """Snapshot of the currently-waiting pods (IterateOverWaitingPods
        — what coscheduling plugins walk to release a whole group)."""
        with self._lock:
            return list(self._pods.values())

    def allow(self, pod: api.Pod) -> bool:
        wp = self.get(pod)
        if wp is None:
            return False
        wp.allow()
        return True

    def reject(self, pod: api.Pod, reason: str = "rejected") -> bool:
        wp = self.get(pod)
        if wp is None:
            return False
        wp.reject(reason)
        return True
