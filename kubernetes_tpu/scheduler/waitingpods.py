"""The waiting-pods map — Permit's asynchronous half.

Reference: pkg/scheduler/framework/runtime/waiting_pods_map.go + the
Permit extension point (framework/interface.go:330-666): a Permit
plugin may return Wait with a timeout; the pod parks in the waiting map
while its binding goroutine blocks in WaitOnPermit
(schedule_one.go:278).  Any plugin may later Allow or Reject it; the
timeout rejects.  This is the extension point real coscheduling
plugins are built on (scheduler/coscheduling.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..api import types as api
from .queue import pod_key


class WaitingPod:
    """One pod parked at Permit (waitingPod, waiting_pods_map.go:52)."""

    def __init__(self, pod: api.Pod, node: str, timeout: float):
        self.pod = pod
        self.node = node
        self.deadline = time.monotonic() + timeout
        self._done = threading.Event()
        self._verdict: Optional[str] = None  # "allow" | reason string

    def allow(self) -> None:
        self._verdict = "allow"
        self._done.set()

    def reject(self, reason: str = "rejected") -> None:
        if self._verdict is None:
            self._verdict = reason
        self._done.set()

    def wait(self) -> str:
        """Block until Allow/Reject/timeout (WaitOnPermit); returns
        "allow" or the rejection reason ("timeout" when the permit
        window lapsed)."""
        remaining = self.deadline - time.monotonic()
        if not self._done.wait(timeout=max(remaining, 0)):
            self.reject("timeout")
        return self._verdict or "rejected"


class WaitingPodsMap:
    def __init__(self):
        self._lock = threading.Lock()
        self._pods: Dict[str, WaitingPod] = {}

    def add(self, wp: WaitingPod) -> None:
        with self._lock:
            self._pods[pod_key(wp.pod)] = wp

    def remove(self, pod: api.Pod) -> None:
        with self._lock:
            self._pods.pop(pod_key(pod), None)

    def get(self, pod: api.Pod) -> Optional[WaitingPod]:
        with self._lock:
            return self._pods.get(pod_key(pod))

    def iterate(self) -> List[WaitingPod]:
        """Snapshot of the currently-waiting pods (IterateOverWaitingPods
        — what coscheduling plugins walk to release a whole group)."""
        with self._lock:
            return list(self._pods.values())

    def allow(self, pod: api.Pod) -> bool:
        wp = self.get(pod)
        if wp is None:
            return False
        wp.allow()
        return True

    def reject(self, pod: api.Pod, reason: str = "rejected") -> bool:
        wp = self.get(pod)
        if wp is None:
            return False
        wp.reject(reason)
        return True
