"""PostFilter: preemption evaluator driving the tensorized dry-run.

The reference flow (framework/preemption/preemption.go:150 Preempt):
  1. candidates: nodes where removing lower-priority pods admits the pod
     (findCandidates → dry-run per node, parallel goroutines)
  2. pick the least-disruption candidate (SelectCandidate :316)
  3. prepare: DELETE the victims through the API, clear lower-priority
     nominations (prepareCandidate, default_preemption.go:345)
  4. nominate: pod.status.nominatedNodeName = node; pod requeues and
     schedules onto the freed space on a later cycle

Ours: the per-node dry-run loop is ops.preemption.dry_run_victims (one
device dispatch over all candidates), selection is the same lexicographic
criteria minus PDBs, victims are deleted through the store (informers
unaccount them), and the chosen candidate is verified by a real re-solve
with the victims masked out of the cluster state before anything is
deleted — so every nomination is backed by an actual placement, including
spread/inter-pod families the resource dry-run can't see.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..api import store as st
from ..api import types as api
from ..models.batch_scheduler import TPUBatchScheduler
from ..ops import preemption as pre_ops
from ..utils.vocab import pad_dim
from .cache import SchedulerCache
from .metrics import Registry
from .queue import pod_key

# Reference caps: minCandidateNodesAbsolute=100, percentage 10%
# (defaultpreemption DefaultPreemptionArgs); we keep one flat cap — the
# dry-run is one dispatch so a larger pool costs little.
MAX_CANDIDATES = 256
# How many ranked candidates to verify with a real re-solve before
# giving up (each verification is a single-pod device solve).
MAX_VERIFY = 8


class PreemptionResult:
    __slots__ = ("nominated_node", "victims")

    def __init__(self, nominated_node: str, victims: List[api.Pod]):
        self.nominated_node = nominated_node
        self.victims = victims


class PreemptionEvaluator:
    def __init__(
        self,
        tpu: TPUBatchScheduler,
        cache: SchedulerCache,
        store: st.Store,
        metrics: Optional[Registry] = None,
    ):
        self.tpu = tpu
        self.cache = cache
        self.store = store
        self.metrics = metrics
        # optional client.events.EventRecorder (set by the Scheduler)
        self.events = None
        # PDBAwarePreemption feature gate (set by the Scheduler): off
        # means victim ranking ignores disruption budgets
        self.pdb_aware = True

    # -- eligibility (PodEligibleToPreemptOthers) --------------------------

    def eligible(self, pod: api.Pod) -> bool:
        if pod.spec.preemption_policy == "Never":
            return False
        prio = pod.spec.priority
        state = self.tpu.state
        with self.cache.lock:
            return any(
                p.spec.priority < prio for p in state._pods.values()
            )

    # -- the PostFilter entry ----------------------------------------------

    def preempt(self, pod: api.Pod) -> Optional[PreemptionResult]:
        """Find victims admitting `pod`, verify by re-solve, evict through
        the store, and nominate.  Returns None when no candidate works."""
        # The preemptor must still exist — evicting running pods on behalf
        # of a deleted pod is the worst failure mode (the reference
        # re-fetches the pod before preparing candidates, getUpdatedPod).
        try:
            self.store.get("Pod", pod.meta.name, pod.meta.namespace)
        except KeyError:
            return None
        if self.metrics:
            self.metrics.preemption_attempts.inc("attempted")
        if pod.spec.scheduling_group:
            plan = self._plan_gang(pod)
        else:
            single = self._plan(pod)
            plan = ([(pod, single[0])], single[1]) if single else None
        if plan is None:
            if self.metrics:
                self.metrics.preemption_attempts.inc("no_candidate")
            return None
        nominations, victims = plan
        node_name = next(
            (n for p, n in nominations if pod_key(p) == pod_key(pod)),
            nominations[0][1],
        )
        # Evict: delete through the API *and* unaccount from the cache
        # immediately (remove_pod is idempotent, so the informer's echo of
        # the delete is a no-op).  Without the synchronous unaccount, the
        # next batch could race ahead of the informer, see the pod still
        # unschedulable, and evict a second victim set.
        for v in victims:
            try:
                self.store.delete("Pod", v.meta.name, v.meta.namespace)
            except KeyError:
                pass  # already gone — the freed space is still freed
            self.cache.remove_pod(v)
            if self.events:
                self.events.eventf(
                    v, "Normal", "Preempted",
                    f"Preempted by {pod.meta.namespace}/{pod.meta.name} on "
                    f"node {node_name}",
                )
        # reserve the freed space for the nominee(s): other batches see
        # the reservation; each nominee's own batch excludes it.  Gangs
        # nominate EVERY member to its verified node so the whole group's
        # space is held until the gang lands (all-or-nothing).
        for p, n in nominations:
            self._nominate(p, n)
            self.cache.nominate(p, n)
        if self.metrics:
            self.metrics.preemption_attempts.inc("nominated")
            self.metrics.preemption_victims.observe(len(victims))
        return PreemptionResult(node_name, victims)

    def _nominate(self, pod: api.Pod, node_name: str) -> None:
        # Best-effort status write (the reference's nominatedNodeName
        # PATCH is equally fire-and-forget).  Conflict is a ValueError,
        # not a KeyError — an uncaught race here after victims were
        # already evicted would kill the scheduler thread, so retry once
        # against the fresh object and then give up: the in-cache
        # nomination (cache.nominate) still reserves the space.
        from ..api import store as st

        for _ in range(2):
            try:
                current = self.store.get(
                    "Pod", pod.meta.name, pod.meta.namespace
                )
                current.status.nominated_node_name = node_name
                self.store.update(current)
                return
            except st.NotFound:
                return  # pod deleted while we worked
            except st.Conflict:
                continue  # concurrent writer; re-read and retry once

    # -- planning (findCandidates + SelectCandidate + verify) --------------

    def _plan(
        self, pod: api.Pod
    ) -> Optional[Tuple[str, List[api.Pod]]]:
        """Choose (node, victims) for the pod, verified by a dry-run
        re-solve against the state with the victims removed.

        Lock discipline mirrors schedule_batch's: host-side reads of the
        shared state and snapshot encodes run under the cache lock
        (inside _candidates); the device dispatches (which can hit
        tens-of-seconds first-time XLA compiles) run OUTSIDE it, so
        informer event handling never stalls behind a compile."""
        base = self._candidates(pod)
        if base is None:
            return None
        cands, ranked, min_k = base
        for ci in ranked[:MAX_VERIFY]:
            row, name, victims, _flags = cands[ci]
            chosen = victims[: int(min_k[ci])]
            if self._verify(pod, name, chosen):
                return name, chosen
        self._note_budget_exhausted(pod, len(ranked))
        return None

    def _plan_gang(
        self, pod: api.Pod
    ) -> Optional[Tuple[List[Tuple[api.Pod, str]], List[api.Pod]]]:
        """Gang preemption: victims must admit the WHOLE group, possibly
        spanning nodes.  Greedy multi-node eviction: walk the ranked
        single-node candidates accumulating their victim sets; after each
        addition re-solve ALL pending members with the accumulated
        victims removed (the solver's gang post-pass enforces
        all-or-nothing), stopping at the first victim set under which the
        gang fully places.  Evicting for one member alone could free
        space a still-partial gang can never use — the failure mode that
        previously made gang pods preemption-ineligible."""
        group = pod.spec.scheduling_group
        pods_all, _ = self.store.list("Pod")
        members = [
            p for p in pods_all
            if p.spec.scheduling_group == group and not p.spec.node_name
        ]
        if not members:
            return None
        members.sort(key=pod_key)
        base = self._candidates(pod)
        if base is None:
            return None
        cands, ranked, min_k = base
        victims_accum: List[api.Pod] = []
        chunks: List[List[api.Pod]] = []  # per-candidate contributions
        for ci in ranked[:MAX_VERIFY]:
            row, name, victims, _flags = cands[ci]
            chunk = victims[: int(min_k[ci])]
            victims_accum.extend(chunk)
            chunks.append(chunk)
            placements = self._verify_multi(members, victims_accum)
            if placements and all(n is not None for n in placements):
                return self._shrink_gang_plan(members, chunks, placements)
        self._note_budget_exhausted(pod, len(ranked))
        return None

    def _shrink_gang_plan(self, members, chunks, placements):
        """Shrink pass: an early candidate's victims may be unnecessary
        once later candidates joined the accumulation (the gang fit
        thanks to them alone).  Try dropping each contribution —
        earliest first, since later ones completed the fit — re-verifying
        the remainder; keep any drop that still fully places.  Bounded:
        one re-solve per contributing candidate (<= MAX_VERIFY extra
        dry-runs, only on the success path)."""
        kept = list(chunks)
        best = placements
        for i in range(len(kept) - 1):  # the last chunk completed the fit
            if not kept[i]:
                continue
            trial_victims = [
                v for j, c in enumerate(kept) if j != i for v in c
            ]
            p = self._verify_multi(members, trial_victims)
            if p and all(n is not None for n in p):
                kept[i] = []
                best = p
        victims = [v for c in kept for v in c]
        return list(zip(members, best)), victims

    def _note_budget_exhausted(self, pod: api.Pod, n_ranked: int) -> None:
        """Distinguish 'no candidate' from 'verification budget ran out'
        — a silent cap here reads as full coverage (review finding r3)."""
        if n_ranked <= MAX_VERIFY:
            return
        if self.metrics:
            self.metrics.preemption_attempts.inc("verify_budget_exhausted")
        logging.getLogger(__name__).info(
            "preemption for %s: %d ranked candidates, verification budget "
            "%d exhausted without a confirmed placement",
            pod_key(pod), n_ranked, MAX_VERIFY,
        )

    def _candidates(self, pod: api.Pod):
        """Collect + rank candidate (node, victims) sets: the tensorized
        findCandidates/SelectCandidate half, shared by single-pod and
        gang planning.  Returns (cands, ranked indices, min_k) with
        cands entries (row, node_name, victims, pdb_violation_flags)."""
        state = self.tpu.state
        prio = pod.spec.priority
        pdbs = self._pdbs()
        with self.cache.lock:
            # assumed pods are mid-bind — not evictable (the reference's
            # dry-run also works off the snapshot of *confirmed* state)
            assumed = set(self.cache._assumed.keys())
            static_snap = self._encode_static(pod)
            # candidate victim data is copied out (free vectors, victim
            # usage) so ranking can run lock-free on a consistent view
            cands: List[Tuple[int, str, List[api.Pod], List[bool]]] = []
            free_rows: List[np.ndarray] = []
            usage: Dict[str, np.ndarray] = {}
            r = state._r
            for name, keys in state._pods_by_node.items():
                row = state._rows.get(name)
                if row is None:
                    continue
                victims = [
                    state._pods[k]
                    for k in keys
                    if state._pods[k].spec.priority < prio and k not in assumed
                ]
                if not victims:
                    continue
                victims.sort(key=lambda p: (p.spec.priority, pod_key(p)))
                flags = self._pdb_flags(victims, pdbs)
                # eviction preference: non-violating victims first
                # (stably, keeping priority order within each partition)
                # — the prefix-eviction analogue of the reference's
                # reprieve pass, which tries hardest to KEEP
                # PDB-violating victims (preemption.go:198)
                paired = sorted(
                    zip(victims, flags), key=lambda vf: vf[1]
                )
                victims = [v for v, _ in paired]
                flags = [f for _, f in paired]
                cands.append((row, name, victims, flags))
                free_rows.append(
                    (state.allocatable[row] - state.requested[row]).copy()
                )
                for v in victims:
                    usage[pod_key(v)] = state.builder.pod_usage(v, r)[0]
                if len(cands) >= MAX_CANDIDATES:
                    break
            if not cands:
                return None
            pod_req = state.builder.pod_usage(pod, r)[0]

        static_ok = self._static_row_from_snap(static_snap)
        keep = [i for i, c in enumerate(cands) if static_ok[c[0]]]
        cands = [cands[i] for i in keep]
        free_rows = [free_rows[i] for i in keep]
        if not cands:
            return None
        ranked, min_k = self._rank(cands, free_rows, usage, pod_req)
        if not ranked:
            return None
        return cands, ranked, min_k

    def _pdbs(self) -> List[api.PodDisruptionBudget]:
        if not self.pdb_aware:
            return []
        try:
            pdbs, _ = self.store.list("PodDisruptionBudget")
        except Exception:
            return []
        return [p for p in pdbs if p.spec.selector is not None]

    @staticmethod
    def _pdb_flags(
        victims: Sequence[api.Pod], pdbs: Sequence[api.PodDisruptionBudget]
    ) -> List[bool]:
        """Per-victim PDB-violation flags (filterPodsWithPDBViolation,
        preemption.go:290): walking the victims in order, each budget's
        first `disruptions_allowed` matching evictions are tolerated;
        evictions past that violate it."""
        if not pdbs:
            return [False] * len(victims)
        allow = [p.status.disruptions_allowed for p in pdbs]
        flags = []
        for v in victims:
            matched = [i for i, p in enumerate(pdbs) if p.matches(v)]
            viol = any(allow[i] <= 0 for i in matched)
            if not viol:
                for i in matched:
                    allow[i] -= 1
            flags.append(viol)
        return flags

    def _rank(
        self,
        cands: Sequence[Tuple[int, str, List[api.Pod], List[bool]]],
        free_rows: Sequence[np.ndarray],
        usage: Dict[str, np.ndarray],
        pod_req: np.ndarray,
    ) -> Tuple[List[int], np.ndarray]:
        """Run the device dry-run over all candidates (lock-free — inputs
        were copied out under the lock); return candidate indices ranked
        most-preferred first (feasible only) plus per-candidate victim
        counts."""
        r = pod_req.shape[0]
        c_dim = pad_dim(len(cands), 8)
        k_dim = pad_dim(max(len(c[2]) for c in cands), 4)
        free = np.zeros((c_dim, r), dtype=np.float32)
        victim_req = np.zeros((c_dim, k_dim, r), dtype=np.float32)
        victim_valid = np.zeros((c_dim, k_dim), dtype=bool)
        for ci, (row, _, victims, _flags) in enumerate(cands):
            free[ci] = free_rows[ci]
            for vi, v in enumerate(victims[:k_dim]):
                victim_req[ci, vi] = usage[pod_key(v)]
                victim_valid[ci, vi] = True
        result = pre_ops.dry_run_victims(free, victim_req, victim_valid, pod_req)
        feasible = np.asarray(result.feasible)[: len(cands)]
        min_k = np.asarray(result.min_k)[: len(cands)]
        # min_k == 0 means the pod already fits — that is a scheduling
        # outcome, not a preemption candidate (the reference only reaches
        # PostFilter when no node passed filters; a zero-victim candidate
        # here is a stale-state race and must not cause a nomination)
        feasible = feasible & (min_k > 0)
        # ranking stats with exact integer math (priorities reach ~2e9,
        # past f32's exact envelope) and node-row tie-break — both must
        # match testing/oracle.preempt for the parity contract.  PDB
        # violations rank first (fewest preferred —
        # pickOneNodeForPreemption's minNumPDBViolatingScoreFunc,
        # preemption.go:463).
        big = np.iinfo(np.int64).max
        max_prio = np.full(len(cands), big, dtype=np.int64)
        sum_prio = np.zeros(len(cands), dtype=np.int64)
        n_viol = np.full(len(cands), big, dtype=np.int64)
        rows = np.array([c[0] for c in cands], dtype=np.int64)
        for ci, (_, _, victims, flags) in enumerate(cands):
            if feasible[ci]:
                k = int(min_k[ci])
                prios = [v.spec.priority for v in victims[:k]]
                max_prio[ci] = max(prios)
                sum_prio[ci] = sum(prios)
                n_viol[ci] = sum(flags[:k])
        order = np.lexsort((rows, min_k, sum_prio, max_prio, n_viol))
        return [int(i) for i in order if feasible[i]], min_k

    def _verify(
        self, pod: api.Pod, node_name: str, victims: List[api.Pod]
    ) -> bool:
        """Dry-run re-solve: under the lock, remove the victims from live
        state, encode a snapshot (device_put copies), and restore; solve
        OUTSIDE the lock.  True iff the pod lands on the expected node.
        This is the all-families check the resource-only kernel can't do
        (the reference re-runs the full filter chain in its dry-run)."""
        placements = self._verify_multi([pod], victims, node_name)
        return bool(placements) and placements[0] == node_name

    def _verify_multi(
        self,
        pods: List[api.Pod],
        victims: List[api.Pod],
        fallback_node: Optional[str] = None,
    ) -> Optional[List[Optional[str]]]:
        """Solve `pods` against the state with `victims` removed (state
        restored before returning); placements list, or None on encode
        failure.  The gang path feeds all pending members so the solver's
        all-or-nothing post-pass judges the whole group."""
        state = self.tpu.state
        with self.cache.lock:
            removed = []
            try:
                for v in victims:
                    if state.has_pod(v):
                        state.remove_pod(v)
                        removed.append(v)
                snap, meta = self.tpu.encode_pending(pods)
            finally:
                for v in removed:
                    state.add_pod(v, v.spec.node_name or fallback_node)
        return self.tpu.solve_encoded(snap, meta)

    # -- static feasibility (non-resource filters) --------------------------

    def _encode_static(self, pod: api.Pod):
        """Encode (under the caller-held lock) the single-pod snapshot the
        static-feasibility kernels read; the aliasing cluster leaves are
        host-copied before device_put (which may zero-copy on CPU) so
        later cache mutation can't leak in."""
        snap, _ = self.tpu.builder.build_from_state(self.tpu.state, [pod])
        snap = snap._replace(cluster=jax.tree.map(np.array, snap.cluster))
        return jax.device_put(snap)

    def _static_row_from_snap(self, snap) -> np.ndarray:
        """bool[rows]: NodeName/taints/affinity/validity feasibility of the
        preemptor on every node (resources deliberately excluded — that is
        what eviction frees).  Pure device dispatch — no lock needed."""
        from ..ops.filters import (
            pod_view,
            selector_match,
            static_feasible_for_pod,
        )

        sel_mask = selector_match(snap.cluster, snap.selectors)
        pv = pod_view(snap.pods, 0)
        feas = static_feasible_for_pod(snap.cluster, pv, sel_mask)
        return np.asarray(feas)
