"""PostFilter: preemption evaluator driving the tensorized dry-run.

The reference flow (framework/preemption/preemption.go:150 Preempt):
  1. candidates: nodes where removing lower-priority pods admits the pod
     (findCandidates → dry-run per node, parallel goroutines)
  2. pick the least-disruption candidate (SelectCandidate :316)
  3. prepare: DELETE the victims through the API, clear lower-priority
     nominations (prepareCandidate, default_preemption.go:345)
  4. nominate: pod.status.nominatedNodeName = node; pod requeues and
     schedules onto the freed space on a later cycle

Ours: the dry-run loop is batched at PASS granularity.  A PostFilter
pass opens a shared context (``shared_pass``) that walks
``state._pods_by_node`` ONCE, encodes the per-node victim tensors
(sorted by priority, PDB-aware eviction order per preemptor priority
level) and runs ONE ``[P, N, K]`` device dry-run plus one batched
static-feasibility dispatch for EVERY failed pod of the cycle
(ops.preemption.batched_dry_run).  Each ``preempt()`` call then ranks
its candidates from the shared tensors; selection is the same
lexicographic criteria (PDB violations first), victims are deleted
through the store (informers unaccount them), and the chosen candidate
is verified by a real re-solve with the victims masked out of the
cluster state before anything is deleted — so every nomination is
backed by an actual placement, including spread/inter-pod families the
resource dry-run can't see.

Cross-preemptor conflicts resolve with a wavefront-style pass
(mirroring ops.assign.plan_waves' coupling discipline): preemptors are
processed in priority order, and the shared dry-run stays valid for a
pod exactly while no earlier preemptor of the pass evicted on its
candidate nodes.  A node an earlier eviction TOUCHED is recomputed
from live state (counted in preemption_conflict_serializations), so two
preemptors never claim overlapping victims or double-count freed
capacity — batched results are identical to running the sequential
``preempt()`` loop (tests/test_preemption.py parity suite).

The sequential per-pod path (no shared context) is kept bit-for-bit as
the exact-parity fallback: the batched encode/dry-run runs behind the
device-solve circuit breaker, and any batched-dispatch failure (after
one retry) trips the breaker and falls the pass back to it.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..api import store as st
from ..api import types as api
from ..models.batch_scheduler import TPUBatchScheduler
from ..ops import preemption as pre_ops
from ..testing import faults
from ..utils.vocab import pad_dim
from .cache import SchedulerCache
from .metrics import Registry
from .queue import pod_key

# Reference caps: minCandidateNodesAbsolute=100, percentage 10%
# (defaultpreemption DefaultPreemptionArgs); we keep one flat cap — the
# dry-run is one dispatch so a larger pool costs little.
MAX_CANDIDATES = 256
# How many ranked candidates to verify with a real re-solve before
# giving up (each verification is a single-pod device solve).
MAX_VERIFY = 8

# sentinel: pod not covered by the active shared pass — route to the
# classic per-pod path (distinct from None = "no candidates")
_MISS = object()


class PreemptionResult:
    __slots__ = ("nominated_node", "victims")

    def __init__(self, nominated_node: str, victims: List[api.Pod]):
        self.nominated_node = nominated_node
        self.victims = victims


class _SharedPass:
    """One PostFilter pass's shared preemption state: the single
    ``_pods_by_node`` walk, the batched device dry-run results, and the
    conflict bookkeeping (``touched``) that keeps batched == sequential.
    Built under the cache lock by ``_begin_shared``; consumed lock-free
    except for touched-node recomputes."""

    __slots__ = (
        "fallback", "empty", "min_prio", "index", "level_of", "nodes",
        "victims", "free", "elig_len", "perm", "viol", "feasible",
        "min_k", "viol_k", "static_ok", "pods_req", "pdbs", "touched",
        "touch_all", "_ordered",
    )

    def __init__(self):
        self.fallback = False    # breaker open / batched dispatch failed
        self.empty = True        # no candidate nodes encoded
        self.min_prio: Optional[int] = None
        self.index: Dict[str, int] = {}     # pod key -> batch row
        self.level_of: Dict[int, int] = {}  # priority -> level row
        self.nodes: List[Tuple[int, str]] = []   # (state row, node name)
        self.victims: List[List[api.Pod]] = []   # per node, (prio, key) asc
        self.free: Optional[np.ndarray] = None       # f32[N, R]
        self.elig_len: Optional[np.ndarray] = None   # i32[L, N]
        self.perm: Optional[np.ndarray] = None       # i32[L, N, K]
        self.viol: Optional[np.ndarray] = None       # bool[L, N, K]
        self.feasible: Optional[np.ndarray] = None   # bool[P, N]
        self.min_k: Optional[np.ndarray] = None      # i32[P, N]
        self.viol_k: Optional[np.ndarray] = None     # i32[P, N]
        self.static_ok: Optional[np.ndarray] = None  # bool[P, rows]
        self.pods_req: Optional[np.ndarray] = None   # f32[P, R]
        self.pdbs: List[api.PodDisruptionBudget] = []
        self.touched: set = set()   # node names an eviction dirtied
        self.touch_all = False      # a victim's node was unknown: degrade
        self._ordered: Dict[Tuple[int, int], Tuple[list, list]] = {}

    def ordered(self, lvl: int, j: int) -> Tuple[list, list]:
        """(victims, pdb flags) of node j in level lvl's eviction order
        (PDB-clean first, priority ascending within each partition)."""
        key = (lvl, j)
        hit = self._ordered.get(key)
        if hit is None:
            e = int(self.elig_len[lvl, j])
            vs = [self.victims[j][i] for i in self.perm[lvl, j, :e]]
            flags = [bool(f) for f in self.viol[lvl, j, :e]]
            hit = self._ordered[key] = (vs, flags)
        return hit


class PreemptionEvaluator:
    def __init__(
        self,
        tpu: TPUBatchScheduler,
        cache: SchedulerCache,
        store: st.Store,
        metrics: Optional[Registry] = None,
    ):
        self.tpu = tpu
        self.cache = cache
        self.store = store
        self.metrics = metrics
        # optional client.events.EventRecorder (set by the Scheduler)
        self.events = None
        # PDBAwarePreemption feature gate (set by the Scheduler): off
        # means victim ranking ignores disruption budgets
        self.pdb_aware = True
        # the active shared PostFilter pass (None outside shared_pass);
        # only the scheduling thread opens/consumes it
        self._shared: Optional[_SharedPass] = None

    # -- eligibility (PodEligibleToPreemptOthers) --------------------------

    def min_existing_priority(self) -> Optional[int]:
        """The cluster's lowest bound/assumed pod priority, or None when
        no pods exist — computed ONCE per PostFilter pass (shared_pass
        caches it) instead of scanning ``state._pods`` per failed pod."""
        state = self.tpu.state
        with self.cache.lock:
            return min(
                (p.spec.priority for p in state._pods.values()),
                default=None,
            )

    def eligible(self, pod: api.Pod) -> bool:
        if pod.spec.preemption_policy == "Never":
            return False
        ctx = self._shared
        if ctx is not None:
            min_prio = ctx.min_prio
        else:
            min_prio = self.min_existing_priority()
        return min_prio is not None and min_prio < pod.spec.priority

    # -- the batched PostFilter pass ---------------------------------------

    @contextlib.contextmanager
    def shared_pass(self, pods: Sequence[api.Pod]):
        """Open the shared preemption context for one PostFilter pass:
        every ``preempt()`` call inside the block consumes the single
        batched encode + dry-run instead of walking the cluster itself.
        Nested entry is a passthrough (one context per pass)."""
        if self._shared is not None:
            yield self._shared
            return
        ctx = self._begin_shared(list(pods))
        self._shared = ctx
        try:
            yield ctx
        finally:
            self._shared = None

    def preempt_batch(
        self, pods: Sequence[api.Pod]
    ) -> List[Optional[PreemptionResult]]:
        """Batched PostFilter: one shared encode + device dry-run for the
        whole failed-pod set, then the per-pod select/verify/evict tail
        in order.  Results are identical to calling ``preempt()``
        sequentially on the same set (the conflict pass recomputes
        touched nodes); on a tripped breaker or a failed batched
        dispatch the pass transparently IS that sequential loop."""
        out: List[Optional[PreemptionResult]] = []
        with self.shared_pass(pods):
            for pod in pods:
                if not self.eligible(pod):
                    out.append(None)
                    continue
                out.append(self.preempt(pod))
        return out

    def _begin_shared(self, pods: List[api.Pod]) -> _SharedPass:
        ctx = _SharedPass()
        ctx.min_prio = self.min_existing_priority()
        elig = [
            p for p in pods
            if p.spec.preemption_policy != "Never"
            and ctx.min_prio is not None
            and ctx.min_prio < p.spec.priority
        ]
        if not elig:
            return ctx
        breaker = getattr(self.tpu, "breaker", None)
        if breaker is not None and breaker.state_code() != 0.0:
            # device path is sick: the pass runs on the exact-parity
            # per-pod fallback until the breaker closes again
            ctx.fallback = True
            return ctx
        try:
            self._encode_and_dispatch(ctx, elig)
        except Exception:  # noqa: BLE001 — batched dispatch fault
            logging.getLogger(__name__).exception(
                "batched preemption dry-run failed; retrying once"
            )
            try:
                self._encode_and_dispatch(ctx, elig)
            except Exception:  # noqa: BLE001
                if breaker is not None:
                    breaker.record_failure()
                logging.getLogger(__name__).exception(
                    "batched preemption retry failed; falling back to the "
                    "per-pod path for this pass"
                )
                ctx.fallback = True
        return ctx

    def _encode_and_dispatch(
        self, ctx: _SharedPass, elig: List[api.Pod]
    ) -> None:
        """The tentpole: walk ``_pods_by_node`` once, build the padded
        victim tensors + per-level eviction orders, dispatch ONE batched
        dry-run and ONE batched static-feasibility solve for the whole
        failed-pod set."""
        t0 = time.perf_counter()
        state = self.tpu.state
        pdbs = self._pdbs()
        levels = sorted({p.spec.priority for p in elig})
        prio_max = levels[-1]
        with self.cache.lock:
            assumed = set(self.cache._assumed.keys())
            r = state._r
            nodes: List[Tuple[int, str]] = []
            victims_l: List[List[api.Pod]] = []
            prios_l: List[np.ndarray] = []
            free_l: List[np.ndarray] = []
            usage: Dict[str, np.ndarray] = {}
            for name, keys in state._pods_by_node.items():
                row = state._rows.get(name)
                if row is None:
                    continue
                vs = [
                    state._pods[k]
                    for k in keys
                    if state._pods[k].spec.priority < prio_max
                    and k not in assumed
                ]
                if not vs:
                    continue
                vs.sort(key=lambda p: (p.spec.priority, pod_key(p)))
                nodes.append((row, name))
                victims_l.append(vs)
                prios_l.append(
                    np.array([v.spec.priority for v in vs], dtype=np.int64)
                )
                free_l.append(
                    (state.allocatable[row] - state.requested[row]).copy()
                )
                for v in vs:
                    vk = pod_key(v)
                    if vk not in usage:
                        usage[vk] = state.builder.pod_usage(v, r)[0]
            ctx.pods_req = np.stack(
                [state.builder.pod_usage(p, r)[0] for p in elig]
            ).astype(np.float32)
            # the static-feasibility snapshot for ALL preemptors at once
            # (the aliasing cluster leaves are host-copied before
            # device_put — see the classic _encode_static)
            snap, _ = self.tpu.builder.build_from_state(state, elig)
            snap = snap._replace(
                cluster=jax.tree.map(np.array, snap.cluster)
            )
        ctx.pdbs = pdbs
        ctx.index = {pod_key(p): i for i, p in enumerate(elig)}
        ctx.level_of = {prio: i for i, prio in enumerate(levels)}
        ctx.nodes = nodes
        ctx.victims = victims_l
        if self.metrics:
            self.metrics.preemption_batch_size.observe(float(len(elig)))
        if not nodes:
            # no node holds an evictable pod: nothing to dry-run, but the
            # static mask is unneeded too — every preempt() returns None
            ctx.empty = True
            if self.metrics:
                self.metrics.preemption_solve_duration.observe(
                    time.perf_counter() - t0
                )
            return
        n = len(nodes)
        k_max = max(len(v) for v in victims_l)
        n_pad = pad_dim(n, 8)
        k_pad = pad_dim(k_max, 4)
        l_pad = pad_dim(len(levels), 1)
        p_pad = pad_dim(len(elig), 4)
        r = ctx.pods_req.shape[1]
        free = np.zeros((n_pad, r), dtype=np.float32)
        victim_req = np.zeros((n_pad, k_pad, r), dtype=np.float32)
        perm = np.tile(
            np.arange(k_pad, dtype=np.int32), (l_pad, n_pad, 1)
        )
        elig_len = np.zeros((l_pad, n_pad), dtype=np.int32)
        viol = np.zeros((l_pad, n_pad, k_pad), dtype=bool)
        for j, vs in enumerate(victims_l):
            free[j] = free_l[j]
            for vi, v in enumerate(vs[:k_pad]):
                victim_req[j, vi] = usage[pod_key(v)]
        for li, level in enumerate(levels):
            for j, vs in enumerate(victims_l):
                e = int(np.searchsorted(prios_l[j], level, side="left"))
                elig_len[li, j] = e
                if e == 0:
                    continue
                if pdbs:
                    flags = self._pdb_flags(vs[:e], pdbs)
                    if any(flags):
                        # eviction preference: non-violating victims
                        # first, stably (the prefix-eviction analogue of
                        # the reference's reprieve pass)
                        order = sorted(range(e), key=lambda i: flags[i])
                        perm[li, j, :e] = np.array(order, dtype=np.int32)
                        viol[li, j, :e] = np.array(
                            [flags[i] for i in order], dtype=bool
                        )
        pods_req = np.zeros((p_pad, r), dtype=np.float32)
        pods_req[: len(elig)] = ctx.pods_req
        pod_level = np.zeros(p_pad, dtype=np.int32)
        for i, p in enumerate(elig):
            pod_level[i] = ctx.level_of[p.spec.priority]
        batch = pre_ops.PreemptionBatch(
            free=free, victim_req=victim_req, perm=perm,
            elig_len=elig_len, viol=viol, pods_req=pods_req,
            pod_level=pod_level,
        )
        self._prewarm_batch(batch)
        act = faults.fire("batch.preemption", pods=len(elig), nodes=n)
        result = pre_ops.run_batched_dry_run(batch)
        static = pre_ops.run_static_feasible_batch(
            snap.cluster, snap.pods, snap.selectors
        )
        got = jax.device_get((result, static))  # one coalesced readback
        res, static_np = got
        min_k = np.asarray(res.min_k)
        if act == faults.CORRUPT:
            # injected device corruption: poison the result so the
            # health check below trips (the NaN-grade fault family)
            min_k = np.full_like(min_k, -1)
        if (min_k < 0).any() or (min_k > k_pad).any():
            # health check (the breaker's non-finite-score analogue): a
            # structurally-broken result means none of this pass's
            # candidate stats can be trusted
            raise RuntimeError(
                "batched preemption dry-run returned out-of-range victim "
                "counts — result untrusted"
            )
        ctx.free = free
        ctx.elig_len = elig_len
        ctx.perm = perm
        ctx.viol = viol
        ctx.feasible = np.asarray(res.feasible)[: len(elig), :n]
        ctx.min_k = min_k[: len(elig), :n]
        ctx.viol_k = np.asarray(res.viol_k)[: len(elig), :n]
        ctx.static_ok = np.asarray(static_np)[: len(elig)]
        ctx.empty = False
        if self.metrics:
            self.metrics.preemption_solve_duration.observe(
                time.perf_counter() - t0
            )

    def _prewarm_batch(self, batch: pre_ops.PreemptionBatch) -> None:
        """First-seen preemption-batch shape: speculatively compile the
        neighbor pod buckets off-thread (SolverPrewarmPool), so churn
        walking the failed-pod bucket ladder never compiles on the
        scheduling thread (same discipline as the solver kernels)."""
        pool = getattr(self.tpu, "prewarm_pool", None)
        if pool is None:
            return
        l, n, k = batch.perm.shape
        p, r = batch.pods_req.shape
        key = ("preempt", l, n, k, p, r)
        if not pool.mark_seen(key):
            return

        def abstract(p_variant: int):
            def redim(arr, want_p=False):
                shape = (p_variant,) + arr.shape[1:] if want_p else arr.shape
                return jax.ShapeDtypeStruct(shape, arr.dtype)

            return pre_ops.PreemptionBatch(
                free=redim(batch.free),
                victim_req=redim(batch.victim_req),
                perm=redim(batch.perm),
                elig_len=redim(batch.elig_len),
                viol=redim(batch.viol),
                pods_req=redim(batch.pods_req, want_p=True),
                pod_level=redim(batch.pod_level, want_p=True),
            )

        for p_variant in (p * 2, p // 2):
            if p_variant < 4:
                continue
            nkey = ("preempt", l, n, k, p_variant, r)
            shapes = abstract(p_variant)

            def compile_fn(shapes=shapes):
                pre_ops.run_batched_dry_run.jitted.lower(shapes).compile()

            pool.offer(nkey, f"preempt/p={p_variant}", compile_fn)

    # -- the PostFilter entry ----------------------------------------------

    def preempt(self, pod: api.Pod) -> Optional[PreemptionResult]:
        """Find victims admitting `pod`, verify by re-solve, evict through
        the store, and nominate.  Returns None when no candidate works."""
        # The preemptor must still exist — evicting running pods on behalf
        # of a deleted pod is the worst failure mode (the reference
        # re-fetches the pod before preparing candidates, getUpdatedPod).
        try:
            self.store.get("Pod", pod.meta.name, pod.meta.namespace)
        except KeyError:
            return None
        if self.metrics:
            self.metrics.preemption_attempts.inc("attempted")
        if pod.spec.scheduling_group:
            plan = self._plan_gang(pod)
        else:
            single = self._plan(pod)
            plan = ([(pod, single[0])], single[1]) if single else None
        if plan is None:
            if self.metrics:
                self.metrics.preemption_attempts.inc("no_candidate")
            return None
        nominations, victims = plan
        node_name = next(
            (n for p, n in nominations if pod_key(p) == pod_key(pod)),
            nominations[0][1],
        )
        # Evict: delete through the API *and* unaccount from the cache
        # immediately (remove_pod is idempotent, so the informer's echo of
        # the delete is a no-op).  Without the synchronous unaccount, the
        # next batch could race ahead of the informer, see the pod still
        # unschedulable, and evict a second victim set.
        ctx = self._shared
        for v in victims:
            if ctx is not None:
                # conflict bookkeeping: a later preemptor of this pass
                # must not trust the shared dry-run on this node
                if v.spec.node_name:
                    ctx.touched.add(v.spec.node_name)
                else:
                    ctx.touch_all = True
            try:
                self.store.delete("Pod", v.meta.name, v.meta.namespace)
            except KeyError:
                pass  # already gone — the freed space is still freed
            self.cache.remove_pod(v)
            if self.events:
                self.events.eventf(
                    v, "Normal", "Preempted",
                    f"Preempted by {pod.meta.namespace}/{pod.meta.name} on "
                    f"node {node_name}",
                )
        # reserve the freed space for the nominee(s): other batches see
        # the reservation; each nominee's own batch excludes it.  Gangs
        # nominate EVERY member to its verified node so the whole group's
        # space is held until the gang lands (all-or-nothing).
        for p, n in nominations:
            self._nominate(p, n)
            self.cache.nominate(p, n)
        if self.metrics:
            self.metrics.preemption_attempts.inc("nominated")
            self.metrics.preemption_victims.observe(len(victims))
        return PreemptionResult(node_name, victims)

    def _nominate(self, pod: api.Pod, node_name: str) -> None:
        # Best-effort status write (the reference's nominatedNodeName
        # PATCH is equally fire-and-forget).  Conflict is a ValueError,
        # not a KeyError — an uncaught race here after victims were
        # already evicted would kill the scheduler thread, so retry once
        # against the fresh object and then give up: the in-cache
        # nomination (cache.nominate) still reserves the space.
        from ..api import store as st

        for _ in range(2):
            try:
                current = self.store.get(
                    "Pod", pod.meta.name, pod.meta.namespace
                )
                current.status.nominated_node_name = node_name
                self.store.update(current)
                return
            except st.NotFound:
                return  # pod deleted while we worked
            except st.Conflict:
                continue  # concurrent writer; re-read and retry once

    # -- planning (findCandidates + SelectCandidate + verify) --------------

    def _plan(
        self, pod: api.Pod
    ) -> Optional[Tuple[str, List[api.Pod]]]:
        """Choose (node, victims) for the pod, verified by a dry-run
        re-solve against the state with the victims removed.

        Lock discipline mirrors schedule_batch's: host-side reads of the
        shared state and snapshot encodes run under the cache lock
        (inside _candidates); the device dispatches (which can hit
        tens-of-seconds first-time XLA compiles) run OUTSIDE it, so
        informer event handling never stalls behind a compile."""
        base = self._candidates(pod)
        if base is None:
            return None
        cands, ranked, min_k = base
        for ci in ranked[:MAX_VERIFY]:
            row, name, victims, _flags = cands[ci]
            chosen = victims[: int(min_k[ci])]
            if self._verify(pod, name, chosen):
                return name, chosen
        self._note_budget_exhausted(pod, len(ranked))
        return None

    def _plan_gang(
        self, pod: api.Pod
    ) -> Optional[Tuple[List[Tuple[api.Pod, str]], List[api.Pod]]]:
        """Gang preemption: victims must admit the WHOLE group, possibly
        spanning nodes.  Greedy multi-node eviction: walk the ranked
        single-node candidates accumulating their victim sets; after each
        addition re-solve ALL pending members with the accumulated
        victims removed (the solver's gang post-pass enforces
        all-or-nothing), stopping at the first victim set under which the
        gang fully places.  Evicting for one member alone could free
        space a still-partial gang can never use — the failure mode that
        previously made gang pods preemption-ineligible."""
        group = pod.spec.scheduling_group
        pods_all, _ = self.store.list("Pod")
        members = [
            p for p in pods_all
            if p.spec.scheduling_group == group and not p.spec.node_name
        ]
        if not members:
            return None
        members.sort(key=pod_key)
        base = self._candidates(pod)
        if base is None:
            return None
        cands, ranked, min_k = base
        victims_accum: List[api.Pod] = []
        chunks: List[List[api.Pod]] = []  # per-candidate contributions
        for ci in ranked[:MAX_VERIFY]:
            row, name, victims, _flags = cands[ci]
            chunk = victims[: int(min_k[ci])]
            victims_accum.extend(chunk)
            chunks.append(chunk)
            placements = self._verify_multi(members, victims_accum)
            if placements and all(n is not None for n in placements):
                return self._shrink_gang_plan(members, chunks, placements)
        self._note_budget_exhausted(pod, len(ranked))
        return None

    def _shrink_gang_plan(self, members, chunks, placements):
        """Shrink pass: an early candidate's victims may be unnecessary
        once later candidates joined the accumulation (the gang fit
        thanks to them alone).  Try dropping each contribution —
        earliest first, since later ones completed the fit —
        re-verifying the remainder; keep any drop that still fully
        places.  Bounded: one re-solve per contributing candidate
        (<= MAX_VERIFY extra dry-runs, only on the success path)."""
        kept = list(chunks)
        best = placements
        for i in range(len(kept) - 1):  # the last chunk completed the fit
            if not kept[i]:
                continue
            trial_victims = [
                v for j, c in enumerate(kept) if j != i for v in c
            ]
            p = self._verify_multi(members, trial_victims)
            if p and all(n is not None for n in p):
                kept[i] = []
                best = p
        victims = [v for c in kept for v in c]
        return list(zip(members, best)), victims

    def _note_budget_exhausted(self, pod: api.Pod, n_ranked: int) -> None:
        """Distinguish 'no candidate' from 'verification budget ran out'
        — a silent cap here reads as full coverage (review finding r3)."""
        if n_ranked <= MAX_VERIFY:
            return
        if self.metrics:
            self.metrics.preemption_attempts.inc("verify_budget_exhausted")
        logging.getLogger(__name__).info(
            "preemption for %s: %d ranked candidates, verification budget "
            "%d exhausted without a confirmed placement",
            pod_key(pod), n_ranked, MAX_VERIFY,
        )

    def _candidates(self, pod: api.Pod):
        """Collect + rank candidate (node, victims) sets: the tensorized
        findCandidates/SelectCandidate half, shared by single-pod and
        gang planning.  Returns (cands, ranked indices, min_k) with
        cands entries (row, node_name, victims, pdb_violation_flags).

        Inside an active shared pass the stats come from the batched
        dry-run (one encode + one dispatch for the whole pass);
        otherwise — and for pods the pass did not cover — the classic
        per-pod walk runs (the exact-parity fallback)."""
        ctx = self._shared
        if ctx is not None and not ctx.fallback:
            got = self._candidates_shared(pod, ctx)
            if got is not _MISS:
                return got
        return self._candidates_classic(pod)

    def _candidates_shared(self, pod: api.Pod, ctx: _SharedPass):
        pi = ctx.index.get(pod_key(pod))
        if pi is None:
            return _MISS
        if ctx.empty:
            return None
        lvl = ctx.level_of[pod.spec.priority]
        cands: List[Tuple[int, str, List[api.Pod], List[bool]]] = []
        feas_list: List[bool] = []
        min_k_list: List[int] = []
        viol_list: List[int] = []
        with self.cache.lock:
            for j, (row, name) in enumerate(ctx.nodes):
                if ctx.touch_all or name in ctx.touched:
                    # wavefront conflict serialization: an earlier
                    # preemptor of this pass evicted here — the shared
                    # dry-run no longer describes this node, recompute
                    # it from live state (exactly what the sequential
                    # loop would see)
                    rec = self._recompute_node(ctx, name, row, pod)
                    if self.metrics:
                        self.metrics.preemption_conflict_serializations.inc()
                    if rec is None:
                        continue
                    victims, flags, feas, mk, vk = rec
                else:
                    if int(ctx.elig_len[lvl, j]) == 0:
                        continue
                    victims, flags = ctx.ordered(lvl, j)
                    feas = bool(ctx.feasible[pi, j])
                    mk = int(ctx.min_k[pi, j])
                    vk = int(ctx.viol_k[pi, j])
                cands.append((row, name, victims, flags))
                feas_list.append(feas)
                min_k_list.append(mk)
                viol_list.append(vk)
                if len(cands) >= MAX_CANDIDATES:
                    break
        if not cands:
            return None
        static_ok = ctx.static_ok[pi]
        keep = [i for i, c in enumerate(cands) if static_ok[c[0]]]
        cands = [cands[i] for i in keep]
        feas_list = [feas_list[i] for i in keep]
        min_k_list = [min_k_list[i] for i in keep]
        viol_list = [viol_list[i] for i in keep]
        if not cands:
            return None
        min_k = np.array(min_k_list, dtype=np.int32)
        # min_k == 0 means the pod already fits — that is a scheduling
        # outcome, not a preemption candidate (see _rank_classic)
        feasible = np.array(feas_list, dtype=bool) & (min_k > 0)
        ranked = self._order_candidates(
            cands, feasible, min_k, np.array(viol_list, dtype=np.int64)
        )
        if not ranked:
            return None
        return cands, ranked, min_k

    def _recompute_node(
        self, ctx: _SharedPass, name: str, row: int, pod: api.Pod
    ):
        """Per-node recompute against LIVE state (caller holds the cache
        lock): the single-node slice of the classic walk plus a host
        mirror of the kernel's f32 cumulative dry-run — bit-identical to
        what a sequential ``preempt()`` would compute after the earlier
        evictions.  Returns (victims, flags, feasible, min_k, viol_k) or
        None when the node no longer holds an eligible victim."""
        state = self.tpu.state
        prio = pod.spec.priority
        assumed = set(self.cache._assumed.keys())
        keys = state._pods_by_node.get(name, ())
        victims = [
            state._pods[k]
            for k in keys
            if state._pods[k].spec.priority < prio and k not in assumed
        ]
        if not victims:
            return None
        victims.sort(key=lambda p: (p.spec.priority, pod_key(p)))
        flags = self._pdb_flags(victims, ctx.pdbs)
        paired = sorted(zip(victims, flags), key=lambda vf: vf[1])
        victims = [v for v, _ in paired]
        flags = [f for _, f in paired]
        r = state._r
        free = (
            state.allocatable[row] - state.requested[row]
        ).astype(np.float32)
        reqs = np.stack(
            [state.builder.pod_usage(v, r)[0] for v in victims]
        ).astype(np.float32)
        cum = np.cumsum(reqs, axis=0)                      # f32, like the kernel
        free_k = np.concatenate(
            [free[None, :], free[None, :] + cum], axis=0
        )                                                  # [K+1, R]
        pod_req = ctx.pods_req[ctx.index[pod_key(pod)]]
        fits = (
            (pod_req[None, :] <= 0) | (pod_req[None, :] <= free_k)
        ).all(axis=-1)
        feasible = bool(fits.any())
        mk = int(np.argmax(fits)) if feasible else 0
        vk = int(sum(flags[:mk]))
        return victims, flags, feasible, mk, vk

    def _candidates_classic(self, pod: api.Pod):
        """The sequential per-pod walk (the exact-parity fallback the
        breaker routes to): one ``_pods_by_node`` scan, one single-pod
        static snapshot, one per-pod device dry-run."""
        state = self.tpu.state
        prio = pod.spec.priority
        pdbs = self._pdbs()
        with self.cache.lock:
            # assumed pods are mid-bind — not evictable (the reference's
            # dry-run also works off the snapshot of *confirmed* state)
            assumed = set(self.cache._assumed.keys())
            static_snap = self._encode_static(pod)
            # candidate victim data is copied out (free vectors, victim
            # usage) so ranking can run lock-free on a consistent view
            cands: List[Tuple[int, str, List[api.Pod], List[bool]]] = []
            free_rows: List[np.ndarray] = []
            usage: Dict[str, np.ndarray] = {}
            r = state._r
            for name, keys in state._pods_by_node.items():
                row = state._rows.get(name)
                if row is None:
                    continue
                victims = [
                    state._pods[k]
                    for k in keys
                    if state._pods[k].spec.priority < prio and k not in assumed
                ]
                if not victims:
                    continue
                victims.sort(key=lambda p: (p.spec.priority, pod_key(p)))
                flags = self._pdb_flags(victims, pdbs)
                # eviction preference: non-violating victims first
                # (stably, keeping priority order within each partition)
                # — the prefix-eviction analogue of the reference's
                # reprieve pass, which tries hardest to KEEP
                # PDB-violating victims (preemption.go:198)
                paired = sorted(
                    zip(victims, flags), key=lambda vf: vf[1]
                )
                victims = [v for v, _ in paired]
                flags = [f for _, f in paired]
                cands.append((row, name, victims, flags))
                free_rows.append(
                    (state.allocatable[row] - state.requested[row]).copy()
                )
                for v in victims:
                    usage[pod_key(v)] = state.builder.pod_usage(v, r)[0]
                if len(cands) >= MAX_CANDIDATES:
                    break
            if not cands:
                return None
            pod_req = state.builder.pod_usage(pod, r)[0]

        static_ok = self._static_row_from_snap(static_snap)
        keep = [i for i, c in enumerate(cands) if static_ok[c[0]]]
        cands = [cands[i] for i in keep]
        free_rows = [free_rows[i] for i in keep]
        if not cands:
            return None
        ranked, min_k = self._rank(cands, free_rows, usage, pod_req)
        if not ranked:
            return None
        return cands, ranked, min_k

    def _pdbs(self) -> List[api.PodDisruptionBudget]:
        if not self.pdb_aware:
            return []
        try:
            pdbs, _ = self.store.list("PodDisruptionBudget")
        except Exception:
            return []
        return [p for p in pdbs if p.spec.selector is not None]

    @staticmethod
    def _pdb_flags(
        victims: Sequence[api.Pod], pdbs: Sequence[api.PodDisruptionBudget]
    ) -> List[bool]:
        """Per-victim PDB-violation flags (filterPodsWithPDBViolation,
        preemption.go:290): walking the victims in order, each budget's
        first `disruptions_allowed` matching evictions are tolerated;
        evictions past that violate it."""
        if not pdbs:
            return [False] * len(victims)
        allow = [p.status.disruptions_allowed for p in pdbs]
        flags = []
        for v in victims:
            matched = [i for i, p in enumerate(pdbs) if p.matches(v)]
            viol = any(allow[i] <= 0 for i in matched)
            if not viol:
                for i in matched:
                    allow[i] -= 1
            flags.append(viol)
        return flags

    def _rank(
        self,
        cands: Sequence[Tuple[int, str, List[api.Pod], List[bool]]],
        free_rows: Sequence[np.ndarray],
        usage: Dict[str, np.ndarray],
        pod_req: np.ndarray,
    ) -> Tuple[List[int], np.ndarray]:
        """Run the per-pod device dry-run over all candidates (lock-free
        — inputs were copied out under the lock); return candidate
        indices ranked most-preferred first (feasible only) plus
        per-candidate victim counts."""
        r = pod_req.shape[0]
        c_dim = pad_dim(len(cands), 8)
        k_dim = pad_dim(max(len(c[2]) for c in cands), 4)
        free = np.zeros((c_dim, r), dtype=np.float32)
        victim_req = np.zeros((c_dim, k_dim, r), dtype=np.float32)
        victim_valid = np.zeros((c_dim, k_dim), dtype=bool)
        for ci, (row, _, victims, _flags) in enumerate(cands):
            free[ci] = free_rows[ci]
            for vi, v in enumerate(victims[:k_dim]):
                victim_req[ci, vi] = usage[pod_key(v)]
                victim_valid[ci, vi] = True
        result = pre_ops.dry_run_victims(free, victim_req, victim_valid, pod_req)
        feasible = np.asarray(result.feasible)[: len(cands)]
        min_k = np.asarray(result.min_k)[: len(cands)]
        # min_k == 0 means the pod already fits — that is a scheduling
        # outcome, not a preemption candidate (the reference only reaches
        # PostFilter when no node passed filters; a zero-victim candidate
        # here is a stale-state race and must not cause a nomination)
        feasible = feasible & (min_k > 0)
        n_viol = np.zeros(len(cands), dtype=np.int64)
        for ci, (_, _, _victims, flags) in enumerate(cands):
            if feasible[ci]:
                n_viol[ci] = sum(flags[: int(min_k[ci])])
        ranked = self._order_candidates(cands, feasible, min_k, n_viol)
        return ranked, min_k

    def _order_candidates(
        self,
        cands: Sequence[Tuple[int, str, List[api.Pod], List[bool]]],
        feasible: np.ndarray,
        min_k: np.ndarray,
        n_viol_arr: np.ndarray,
    ) -> List[int]:
        """The shared SelectCandidate ordering (both the batched and the
        classic path land here so they cannot diverge): ranking stats
        with exact integer math (priorities reach ~2e9, past f32's exact
        envelope) and node-row tie-break — both must match
        testing/oracle Oracle.preempt for the parity contract.  PDB
        violations rank first (fewest preferred —
        pickOneNodeForPreemption's minNumPDBViolatingScoreFunc,
        preemption.go:463)."""
        big = np.iinfo(np.int64).max
        max_prio = np.full(len(cands), big, dtype=np.int64)
        sum_prio = np.zeros(len(cands), dtype=np.int64)
        n_viol = np.full(len(cands), big, dtype=np.int64)
        rows = np.array([c[0] for c in cands], dtype=np.int64)
        blocked = 0
        for ci, (_, _, victims, _flags) in enumerate(cands):
            if feasible[ci]:
                k = int(min_k[ci])
                prios = [v.spec.priority for v in victims[:k]]
                max_prio[ci] = max(prios)
                sum_prio[ci] = sum(prios)
                n_viol[ci] = int(n_viol_arr[ci])
                if n_viol[ci] > 0:
                    blocked += 1
        if blocked and self.metrics:
            # feasible candidates whose minimal eviction set would
            # violate a disruption budget: the ranking pushes them last
            self.metrics.preemption_pdb_blocked_total.inc(by=float(blocked))
        order = np.lexsort((rows, min_k, sum_prio, max_prio, n_viol))
        return [int(i) for i in order if feasible[i]]

    def _verify(
        self, pod: api.Pod, node_name: str, victims: List[api.Pod]
    ) -> bool:
        """Dry-run re-solve: under the lock, remove the victims from live
        state, encode a snapshot (device_put copies), and restore; solve
        OUTSIDE the lock.  True iff the pod lands on the expected node.
        This is the all-families check the resource-only kernel can't do
        (the reference re-runs the full filter chain in its dry-run)."""
        placements = self._verify_multi([pod], victims, node_name)
        return bool(placements) and placements[0] == node_name

    def _verify_multi(
        self,
        pods: List[api.Pod],
        victims: List[api.Pod],
        fallback_node: Optional[str] = None,
    ) -> Optional[List[Optional[str]]]:
        """Solve `pods` against the state with `victims` removed (state
        restored before returning); placements list, or None on encode
        failure.  The gang path feeds all pending members so the solver's
        all-or-nothing post-pass judges the whole group.

        OTHER preemptors' nominations overlay their nodes as
        reservations (the filters-with-nominated-pods analogue,
        runtime/framework.go:962): without them, a node an earlier
        preemptor of the pass just freed attracts this verify solve,
        failing the legitimate candidate — observed steering evictions
        onto PDB-guarded victims whose node merely had a lower row
        index than the reserved one."""
        state = self.tpu.state
        with self.cache.lock:
            reservations = self.cache.nominations_excluding(
                {pod_key(p) for p in pods}
            )
            removed = []
            try:
                for v in victims:
                    if state.has_pod(v):
                        state.remove_pod(v)
                        removed.append(v)
                snap, meta = self.tpu.encode_pending(
                    pods, reservations=reservations
                )
            finally:
                for v in removed:
                    state.add_pod(v, v.spec.node_name or fallback_node)
        return self.tpu.solve_encoded(snap, meta)

    # -- static feasibility (non-resource filters) --------------------------

    def _encode_static(self, pod: api.Pod):
        """Encode (under the caller-held lock) the single-pod snapshot the
        static-feasibility kernels read; the aliasing cluster leaves are
        host-copied before device_put (which may zero-copy on CPU) so
        later cache mutation can't leak in."""
        snap, _ = self.tpu.builder.build_from_state(self.tpu.state, [pod])
        snap = snap._replace(cluster=jax.tree.map(np.array, snap.cluster))
        return jax.device_put(snap)

    def _static_row_from_snap(self, snap) -> np.ndarray:
        """bool[rows]: NodeName/taints/affinity/validity feasibility of the
        preemptor on every node (resources deliberately excluded — that is
        what eviction frees).  Pure device dispatch — no lock needed."""
        from ..ops.filters import (
            pod_view,
            selector_match,
            static_feasible_for_pod,
        )

        sel_mask = selector_match(snap.cluster, snap.selectors)
        pv = pod_view(snap.pods, 0)
        feas = static_feasible_for_pod(snap.cluster, pv, sel_mask)
        return np.asarray(feas)
