"""Scheduler metrics — the reference's Prometheus surface reduced to an
in-process registry (pkg/scheduler/metrics/metrics.go:89-150,
component-base/metrics wrappers).  Metric *names* are kept identical so
the scheduler_perf collectors scrape the same series the reference's do.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple

# the reference's scheduling-latency bucket layout (metrics.go:92:
# ExponentialBuckets(0.001, 2, 15))
_DEF_BUCKETS = tuple(0.001 * 2 ** i for i in range(15))


class Histogram:
    def __init__(self, name: str, buckets: Tuple[float, ...] = _DEF_BUCKETS):
        self.name = name
        self.buckets = sorted(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0
        self.max = 0.0  # true upper bound for the +Inf bucket
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self.counts[bisect.bisect_left(self.buckets, value)] += 1
            self.total += value
            self.n += 1
            if value > self.max:
                self.max = value

    def percentile(self, q: float) -> float:
        """Linear-interpolated quantile from bucket counts (what the
        perf-harness metricsCollector computes from histograms)."""
        with self._lock:
            if self.n == 0:
                return 0.0
            target = q * self.n
            seen = 0
            lo = 0.0
            for i, c in enumerate(self.counts):
                # the +Inf bucket's bound is the true max observed value
                # (Prometheus would report the last finite bound; fabricating
                # lo*2 would misreport p99s the perf harness quotes)
                hi = (
                    self.buckets[i]
                    if i < len(self.buckets)
                    else max(self.max, lo)
                )
                if seen + c >= target and c > 0:
                    frac = (target - seen) / c
                    return lo + (hi - lo) * frac
                seen += c
                lo = hi
            return lo

    @property
    def average(self) -> float:
        with self._lock:
            return self.total / self.n if self.n else 0.0


class Counter:
    def __init__(self, name: str):
        self.name = name
        self._v: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, *labels: str, by: float = 1.0) -> None:
        with self._lock:
            self._v[labels] = self._v.get(labels, 0.0) + by

    def get(self, *labels: str) -> float:
        with self._lock:
            return self._v.get(labels, 0.0)

    @property
    def total(self) -> float:
        with self._lock:
            return sum(self._v.values())


class Gauge:
    def __init__(self, name: str):
        self.name = name
        self._v: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, *labels: str) -> None:
        with self._lock:
            self._v[labels] = value

    def get(self, *labels: str) -> float:
        with self._lock:
            return self._v.get(labels, 0.0)


class Registry:
    """One scheduler's metric set, by reference name."""

    def __init__(self):
        # metrics.go:89 scheduling_attempt_duration_seconds
        self.scheduling_attempt_duration = Histogram(
            "scheduler_scheduling_attempt_duration_seconds"
        )
        # metrics.go SchedulingAlgorithmLatency
        self.scheduling_algorithm_duration = Histogram(
            "scheduler_scheduling_algorithm_duration_seconds"
        )
        # pod_scheduling_sli_duration_seconds (end-to-end incl. requeues)
        self.pod_scheduling_sli_duration = Histogram(
            "scheduler_pod_scheduling_sli_duration_seconds"
        )
        self.framework_extension_point_duration = Histogram(
            "scheduler_framework_extension_point_duration_seconds"
        )
        # schedule_attempts_total{result="scheduled|unschedulable|error"}
        self.schedule_attempts = Counter("scheduler_schedule_attempts_total")
        # pending_pods{queue="active|backoff|unschedulable|gated"}
        self.pending_pods = Gauge("scheduler_pending_pods")
        self.preemption_victims = Histogram("scheduler_preemption_victims")
        self.preemption_attempts = Counter("scheduler_preemption_attempts_total")

    def snapshot(self) -> Dict[str, object]:
        """Name → metric, for collectors."""
        return {
            m.name: m
            for m in vars(self).values()
            if isinstance(m, (Histogram, Counter, Gauge))
        }
