"""Scheduler metrics — the reference's Prometheus surface reduced to an
in-process registry (pkg/scheduler/metrics/metrics.go:89-150,
component-base/metrics wrappers).  Metric *names* are kept identical so
the scheduler_perf collectors scrape the same series the reference's do.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple

# the reference's scheduling-latency bucket layout (metrics.go:92:
# ExponentialBuckets(0.001, 2, 15))
_DEF_BUCKETS = tuple(0.001 * 2 ** i for i in range(15))


class Histogram:
    def __init__(self, name: str, buckets: Tuple[float, ...] = _DEF_BUCKETS):
        self.name = name
        self.buckets = sorted(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0
        self.max = 0.0  # true upper bound for the +Inf bucket
        self._lock = threading.Lock()

    def observe(self, value: float, count: int = 1) -> None:
        """Record `value`, `count` times.  count>1 is the batched-solve
        fan-out: one device dispatch schedules P pods, so the per-pod
        algorithm cost (solve/P) is observed once per pod without P
        bisect calls."""
        with self._lock:
            self.counts[bisect.bisect_left(self.buckets, value)] += count
            self.total += value * count
            self.n += count
            if value > self.max:
                self.max = value

    def percentile(self, q: float) -> float:
        """Linear-interpolated quantile from bucket counts (what the
        perf-harness metricsCollector computes from histograms)."""
        with self._lock:
            if self.n == 0:
                return 0.0
            target = q * self.n
            seen = 0
            lo = 0.0
            for i, c in enumerate(self.counts):
                # the +Inf bucket's bound is the true max observed value
                # (Prometheus would report the last finite bound; fabricating
                # lo*2 would misreport p99s the perf harness quotes)
                hi = (
                    self.buckets[i]
                    if i < len(self.buckets)
                    else max(self.max, lo)
                )
                if seen + c >= target and c > 0:
                    frac = (target - seen) / c
                    return lo + (hi - lo) * frac
                seen += c
                lo = hi
            return lo

    @property
    def average(self) -> float:
        with self._lock:
            return self.total / self.n if self.n else 0.0


class HistogramVec:
    """A labeled histogram family (component-base metrics HistogramVec):
    one child Histogram per label tuple, created lazily.  snapshot()
    flattens children under `name{label}` so /metrics and collectors see
    plain histograms."""

    def __init__(self, name: str, buckets: Tuple[float, ...] = _DEF_BUCKETS):
        self.name = name
        self.buckets = buckets
        self._children: Dict[Tuple[str, ...], Histogram] = {}
        self._lock = threading.Lock()

    def labels(self, *labels: str) -> Histogram:
        with self._lock:
            h = self._children.get(labels)
            if h is None:
                child_name = (
                    f'{self.name}{{extension_point="{"/".join(labels)}"}}'
                    if labels
                    else self.name
                )
                h = self._children[labels] = Histogram(
                    child_name, self.buckets
                )
            return h

    def children(self) -> Dict[Tuple[str, ...], Histogram]:
        with self._lock:
            return dict(self._children)


class Counter:
    def __init__(self, name: str):
        self.name = name
        self._v: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, *labels: str, by: float = 1.0) -> None:
        with self._lock:
            self._v[labels] = self._v.get(labels, 0.0) + by

    def get(self, *labels: str) -> float:
        with self._lock:
            return self._v.get(labels, 0.0)

    @property
    def total(self) -> float:
        with self._lock:
            return sum(self._v.values())


class Gauge:
    def __init__(self, name: str):
        self.name = name
        self._v: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, *labels: str) -> None:
        with self._lock:
            self._v[labels] = value

    def get(self, *labels: str) -> float:
        with self._lock:
            return self._v.get(labels, 0.0)

    @property
    def total(self) -> float:
        """Sum over every label tuple — equal to the bare value for
        unlabeled gauges; the cross-tier total for labeled ones (what
        the perf collectors report for pending_pods)."""
        with self._lock:
            return sum(self._v.values())


class Registry:
    """One scheduler's metric set, by reference name."""

    def __init__(self):
        # metrics.go:89 scheduling_attempt_duration_seconds
        self.scheduling_attempt_duration = Histogram(
            "scheduler_scheduling_attempt_duration_seconds"
        )
        # metrics.go SchedulingAlgorithmLatency — PER POD: one device
        # dispatch solves a whole batch, so each pod is observed at
        # solve_duration / batch_size (the comparable per-attempt cost;
        # the whole-batch number lives in batch_solve_duration below)
        self.scheduling_algorithm_duration = Histogram(
            "scheduler_scheduling_algorithm_duration_seconds"
        )
        # OUR batch-level metric (no reference analogue): one observation
        # per device solve, including any first-shape XLA compile
        self.batch_solve_duration = Histogram(
            "scheduler_batch_solve_duration_seconds"
        )
        # OUR pipeline metrics (no reference analogue — the reference's
        # binding cycle is per-pod goroutines, ours is batched waves):
        # one full cycle of the solve stage, pop -> solve -> assume ->
        # wave dispatch (commit happens off-thread and is NOT included)
        self.schedule_batch_duration = Histogram(
            "scheduler_schedule_batch_duration_seconds"
        )
        # one observation per bind wave the binding stage commits
        self.commit_wave_duration = Histogram(
            "scheduler_commit_wave_duration_seconds"
        )
        # pods per committed wave (coalescing effectiveness under churn)
        self.commit_wave_size = Histogram(
            "scheduler_commit_wave_size_pods",
            buckets=tuple(float(2 ** i) for i in range(13)),
        )
        # seconds of each wave's commit that ran WHILE a device solve was
        # in flight — the pipeline's realized solve/commit overlap; a
        # healthy pipeline keeps this close to commit_wave_duration
        self.pipeline_overlap = Histogram(
            "scheduler_pipeline_overlap_seconds"
        )
        # one observation per per-store-shard sub-wave the binder
        # commits (the sharded store's per-shard commit durations)
        self.commit_subwave_duration = Histogram(
            "scheduler_commit_subwave_duration_seconds"
        )
        # seconds of sub-wave commit work that ran CONCURRENTLY with
        # another sub-wave of the same wave (sum of sub-wave durations
        # minus the wave's commit wall time) — the realized cross-shard
        # commit overlap; 0 means sub-waves serialized
        self.commit_subwave_overlap = Histogram(
            "scheduler_commit_subwave_overlap_seconds"
        )
        # OUR solve-side pipeline metrics (no reference analogue):
        # waves per wavefront-routed greedy solve (ops.assign wavefront:
        # the scan's P sequential steps collapse to ~P/W)
        self.solve_wave_count = Histogram(
            "scheduler_solve_wave_count",
            buckets=tuple(float(2 ** i) for i in range(13)),
        )
        # fallbacks per wavefront solve: serialized (coupled) waves plus
        # per-pod exact re-evaluations (fit flips) — a high count means
        # the partitioner is mis-planning for this workload
        self.solve_wave_fallbacks = Histogram(
            "scheduler_solve_wave_fallbacks",
            buckets=tuple(float(2 ** i) for i in range(13)),
        )
        # wall seconds of solver executable compiles: synchronous
        # first-shape compiles observed on the dispatch path plus
        # background prewarm-pool compiles (SolverPrewarmPool)
        self.solve_compile_duration = Histogram(
            "scheduler_solve_compile_duration_seconds"
        )
        # seconds of device solve + readback hidden behind host work
        # (the pop window) per group — the realized solve-side overlap;
        # a healthy pipeline keeps this close to the device solve time
        self.decode_overlap = Histogram(
            "scheduler_decode_overlap_seconds"
        )
        # pod_scheduling_sli_duration_seconds (end-to-end incl. requeues)
        self.pod_scheduling_sli_duration = Histogram(
            "scheduler_pod_scheduling_sli_duration_seconds"
        )
        # labeled per extension point (PreEnqueue/Permit/PreBind/...),
        # observed by the Framework runners (framework.py)
        self.framework_extension_point_duration = HistogramVec(
            "scheduler_framework_extension_point_duration_seconds"
        )
        # -- degraded-mode / robustness surface (docs/robustness.md) ------
        # circuit-breaker state: 0 closed, 1 half-open, 2 open
        self.solve_breaker_state = Gauge("scheduler_solve_breaker_state")
        # running total of batches solved on the host fallback path
        # (mirrored from the breaker each cycle — monotonic)
        self.solve_fallback_total = Gauge("scheduler_solve_fallback_total")
        # binding-worker restarts by the watchdog (binder supervision)
        self.binder_restarts = Counter("scheduler_binder_restarts_total")
        # waves that failed twice and were split into per-pod commits
        self.binder_poison_waves = Counter(
            "scheduler_binder_poison_waves_total"
        )
        # corrupt journal records replay survived (mirrored from the
        # store: skipped mid-file lines + truncated torn tails)
        self.journal_recovered_records = Gauge(
            "scheduler_journal_recovered_records"
        )
        # -- crash-restart recovery surface (docs/robustness.md) ----------
        # wall time the store's last recovery took (snapshot load +
        # journal suffix replay), mirrored from the store
        self.store_recovery_duration_ms = Gauge(
            "scheduler_store_recovery_duration_ms"
        )
        # objects the last recovery loaded from the checkpoint snapshot
        self.store_snapshot_records = Gauge(
            "scheduler_store_snapshot_records"
        )
        # journal records the last recovery replayed past the snapshot
        self.store_journal_suffix_records = Gauge(
            "scheduler_store_journal_suffix_records"
        )
        # checkpoints the store has taken (growth/interval/manual)
        self.store_checkpoints_total = Gauge(
            "scheduler_store_checkpoints_total"
        )
        # (kind, namespace)-hash shards the store splits its
        # locks/journals/watch fan-out across (1 = unsharded legacy)
        self.store_shard_count = Gauge("scheduler_store_shard_count")
        # bind waves the store rejected because the committing leader's
        # fence token was stale (a deposed leader's late wave)
        self.fenced_writes_total = Gauge("scheduler_fenced_writes_total")
        # leadership/restart reconciliations the scheduler ran (start,
        # takeover, reacquisition)
        self.leader_reconcile_total = Counter(
            "scheduler_leader_reconcile_total"
        )
        # XLA traces of the solver executables observed by the
        # recompile-discipline runtime tracker (analysis/retrace.py),
        # mirrored each cycle when the tracker is armed (bench runs,
        # GRAFTLINT_SHAPES=1 test sessions); steady-state increments
        # mean a kernel argument escaped the pad-bucket lattice
        self.solve_retrace_total = Gauge("scheduler_solve_retrace_total")
        # -- sharded-solve surface (docs/scheduler_loop.md mesh mode) ------
        # mesh size the solver shards the node axis over (0 single-chip)
        self.solve_shard_count = Gauge("scheduler_solve_shard_count")
        # full mirror re-uploads (struct-generation changes, shape
        # changes, over-fraction deltas) — mirrored from
        # DeviceClusterMirror; steady state should not move
        self.mirror_resync_total = Gauge("scheduler_mirror_resync_total")
        # real dirty rows scattered by mirror delta syncs (running
        # total) — per-batch host→device transfer is O(this delta), not
        # O(N); bench c7 gates on it
        self.mirror_delta_rows = Gauge("scheduler_mirror_delta_rows")
        # batches a configured mesh could not solve sharded (padded node
        # bucket smaller than the mesh) and routed single-chip instead
        self.sharded_solve_fallbacks = Gauge(
            "scheduler_sharded_solve_fallbacks"
        )
        # -- elastic node axis (docs/scheduler_loop.md) --------------------
        # pad-bucket crossings the mirror absorbed with an in-place
        # resident resize (device-side pad/slice) instead of a full
        # re-upload — autoscaler growth should move THIS, not resyncs
        self.mirror_grow_total = Gauge("scheduler_mirror_grow_total")
        # node-axis rows added by in-place grows (running total): the
        # bucket-crossing transfer is O(this delta + dirty rows), not
        # O(N) — bench c12 gates on it
        self.mirror_grow_rows = Gauge("scheduler_mirror_grow_rows")
        # the pad bucket ClusterState currently exposes (post-hysteresis:
        # rises eagerly, falls only after bucketShrinkDwell generations)
        self.node_axis_bucket = Gauge("scheduler_node_axis_bucket")
        # deferred-compaction invocations that did work (trim or move)
        self.compactions_total = Gauge("scheduler_compactions_total")
        # rows relocated by deferred compaction (running total; bounded
        # per invocation by compactionBatchRows — a drain is O(live))
        self.compaction_moved_rows = Gauge(
            "scheduler_compaction_moved_rows"
        )
        # -- incremental-solve surface (docs/scheduler_loop.md) ------------
        # [class, node-row] partials entries served from the resident
        # cache instead of re-evaluated (running total, mirrored from
        # the PartialsCache each cycle)
        self.partials_hit_rows = Gauge("scheduler_partials_hit_rows")
        # node rows re-evaluated by the warm path: dirty-row refreshes
        # plus full rows for first-seen classes — per-batch recompute is
        # O(this delta), not O(C x N)
        self.partials_recomputed_rows = Gauge(
            "scheduler_partials_recomputed_rows"
        )
        # full partials-store recomputes (first sync, struct/vocab
        # invalidation, periodic resync, parity-gate trips); steady
        # state should not move outside the periodic interval
        self.partials_full_recomputes = Gauge(
            "scheduler_partials_full_recomputes_total"
        )
        # speculation rollbacks of the resident partials (invalidated
        # speculative batches — rolled back alongside the mirror)
        self.partials_rollbacks = Gauge(
            "scheduler_partials_rollbacks_total"
        )
        # graftcoh runtime epoch auditor (analysis/epochs.py), mirrored
        # each cycle when GRAFTLINT_COHERENCE=1 arms it (0 disarmed):
        # consume-time resident-epoch audits performed and violations
        # recorded — chaos and BENCH_STRICT runs gate violations == 0
        # with audits > 0
        self.coherence_audits = Gauge("scheduler_coherence_audits_total")
        self.coherence_violations = Gauge(
            "scheduler_coherence_violations_total"
        )
        # graftobl runtime exactly-once ledger (analysis/ledger.py),
        # mirrored each cycle when GRAFTLINT_OBLIGATIONS=1 arms it (all
        # 0 disarmed): obligations tracked, leaked past discharge, and
        # double-discharged — chaos and BENCH_STRICT runs gate leaks ==
        # double-discharges == 0
        self.obligations_tracked = Gauge(
            "scheduler_obligations_tracked_total"
        )
        self.obligation_leaks = Gauge("scheduler_obligation_leaks_total")
        self.obligation_double_discharge = Gauge(
            "scheduler_obligation_double_discharge_total"
        )
        # -- overload-protection surface (docs/robustness.md) -------------
        # deepest per-watcher coalescing backlog at the last cycle mirror
        self.watch_queue_depth = Gauge("scheduler_watch_queue_depth")
        # events compacted away by per-watcher coalescing (latest-wins
        # MODIFIED runs + ADDED/DELETED annihilation), store mirror
        self.watch_coalesced_total = Gauge("scheduler_watch_coalesced_total")
        # watchers expired (bookmark rv + forced relist) after their
        # coalescing buffer overflowed — the survivable-overload path
        self.watch_expired_total = Gauge("scheduler_watch_expired_total")
        # legacy destructive slow-watcher kills, labeled per kind; the
        # backpressured fan-out never performs them (benches assert 0)
        self.watch_terminated_total = Gauge("scheduler_watch_terminated_total")
        # the adaptive accumulation window currently in force
        self.batch_window_ms = Gauge("scheduler_batch_window_ms")
        # overload controller level: 0 healthy / 1 shed background /
        # 2 severe (window pinned wide)
        self.overload_level = Gauge("scheduler_overload_level")
        # background work units (preemption dry-runs) the overload
        # controller deferred instead of letting cycles pile up
        self.overload_shed_total = Counter("scheduler_overload_shed_total")
        # schedule_attempts_total{result="scheduled|unschedulable|error"}
        self.schedule_attempts = Counter("scheduler_schedule_attempts_total")
        # pending_pods{queue="active|backoff|unschedulable|gated"}
        self.pending_pods = Gauge("scheduler_pending_pods")
        self.preemption_victims = Histogram("scheduler_preemption_victims")
        self.preemption_attempts = Counter("scheduler_preemption_attempts_total")
        # -- batched-preemption surface (docs/scheduler_loop.md) -----------
        # wall seconds of one PostFilter pass's shared encode + batched
        # [P, N, K] device dry-run + static-feasibility dispatch (one
        # observation per pass; the per-pod walk this replaced paid this
        # cost per failed pod)
        self.preemption_solve_duration = Histogram(
            "scheduler_preemption_solve_duration_seconds"
        )
        # failed pods sharing one batched preemption solve
        self.preemption_batch_size = Histogram(
            "scheduler_preemption_batch_size_pods",
            buckets=tuple(float(2 ** i) for i in range(13)),
        )
        # wavefront-style conflict serializations: (preemptor, node)
        # pairs recomputed from live state because an earlier preemptor
        # of the same pass evicted there (the coupling discipline that
        # keeps batched == sequential)
        self.preemption_conflict_serializations = Counter(
            "scheduler_preemption_conflict_serializations_total"
        )
        # feasible candidates whose minimal eviction set would violate a
        # PodDisruptionBudget (ranked last — minNumPDBViolatingScoreFunc)
        self.preemption_pdb_blocked_total = Counter(
            "scheduler_preemption_pdb_blocked_total"
        )
        # -- pipelined multi-lane surface (docs/scheduler_loop.md) ---------
        # concurrent profile lanes in force (1 = the serial loop)
        self.lane_count = Gauge("scheduler_lane_count")
        # batches dispatched SPECULATIVELY — encode/solve run while an
        # earlier wave was still committing, over its assumed placements
        self.speculative_solves_total = Counter(
            "scheduler_speculative_solves_total"
        )
        # speculative batches invalidated (a wave they solved over
        # failed/was fenced after their dispatch) and requeued whole
        self.misspeculation_total = Counter("scheduler_misspeculation_total")
        # per streamed sub-wave: milliseconds between its hand-off to
        # the commit pool and the completion of the whole group's
        # staging — the commit lead streaming bought that sub-wave
        self.subwave_stream_lead_ms = Histogram(
            "scheduler_subwave_stream_lead_ms",
            buckets=tuple(0.1 * 2 ** i for i in range(15)),
        )
        # -- TPU slice-topology surface (docs/scheduler_loop.md) -----------
        # cluster-wide fragmentation after the most recent slice-family
        # solve: 1 - (per-slice largest placeable free cube volumes /
        # free devices); 0 = every free device in a maximal cube
        self.fragmentation_score = Gauge("scheduler_fragmentation_score")
        # gangs that anchored a slice carve-out (running total across
        # solves; CoschedulingPermit-released gangs count through the
        # two outcome counters below instead)
        self.slice_carveouts = Counter("scheduler_slice_carveouts_total")
        # shaped gangs fully placed but NOT inside their carve-out
        # (prefer-mode scattered fallbacks; require mode keeps this 0)
        self.slice_carveout_fallbacks = Counter(
            "scheduler_slice_carveout_fallbacks_total"
        )
        # shaped gangs fully placed inside their carved sub-cuboid
        self.gang_contiguous_placements = Counter(
            "scheduler_gang_contiguous_placements_total"
        )
        # -- columnar host plane (docs/scheduler_loop.md host plane) -------
        # pod rows encoded per second by the most recent snapshot build
        # (the columnar spec-row fast path; the host encode's share of
        # the sustained-rate budget)
        self.encode_rows_per_s = Gauge("scheduler_encode_rows_per_s")
        # running bytes of framed journal writes (one serialization +
        # one crc + one write/fsync per commit sub-wave), store mirror
        self.journal_frame_bytes = Gauge("scheduler_journal_frame_bytes")
        # mean events per watch fan-out chunk (batched per-watcher
        # hand-off under one publish-lock hold), store mirror
        self.fanout_chunk_size = Gauge("scheduler_fanout_chunk_size")
        # the c6s ramp hunt's capacity knee: highest arrival rate whose
        # backlog stayed bounded (0 until a ramp-mode bench run sets it)
        self.c6s_arrival_knee = Gauge(
            "scheduler_c6s_arrival_knee_pods_per_s"
        )
        # -- serving plane (docs/robustness.md serving-plane section) ------
        # effective APF seats across all priority levels (shrinks under
        # adaptive pressure, recovers with hysteresis) — mirrored from
        # the replica set's shared gate each cycle
        self.apf_seats_current = Gauge("scheduler_apf_seats_current")
        # requests shed by APF across all levels (429 + Retry-After)
        self.apf_rejected_total = Gauge("scheduler_apf_rejected_total")
        # watch streams expired by the per-watcher HTTP write deadline
        # (stalled TCP consumers), cumulative across killed replicas
        self.server_watch_write_stalls_total = Gauge(
            "scheduler_server_watch_write_stalls_total"
        )
        # replica instances killed out of the serving set (clients fail
        # over to the survivors and re-watch from their last rv)
        self.replica_failovers_total = Gauge(
            "scheduler_replica_failovers_total"
        )
        # -- graftsched surface (docs/static_analysis.md) ------------------
        # deterministic interleaving schedules explored and yield points
        # scheduled across them (analysis/interleave.py TOTALS, mirrored
        # via interleave.mirror_metrics — make race / --interleave runs)
        self.interleave_schedules_total = Gauge(
            "scheduler_interleave_schedules_total"
        )
        self.interleave_yield_points = Gauge(
            "scheduler_interleave_yield_points"
        )
        # findings of the static atomicity pass at the last mirrored
        # lint run (tree-clean CI keeps this 0; mirror_metrics sets it)
        self.atomicity_findings = Gauge("scheduler_atomicity_findings")

    def snapshot(self) -> Dict[str, object]:
        """Name → metric, for collectors.  HistogramVec children appear
        under their labeled names (`name{extension_point="..."}`)."""
        out: Dict[str, object] = {}
        for m in vars(self).values():
            if isinstance(m, (Histogram, Counter, Gauge)):
                out[m.name] = m
            elif isinstance(m, HistogramVec):
                for child in m.children().values():
                    out[child.name] = child
        return out
