"""Health + metrics endpoints for the scheduler process.

Reference: the scheduler binary serves healthz/readyz/livez and an
authenticated /metrics (app/server.go:169-209,
newHealthEndpointsAndMetricsHandler).  /metrics speaks the Prometheus
text exposition format over the in-process Registry so standard scrapers
ingest it.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .metrics import Counter, Gauge, Histogram, Registry


def render_prometheus(registry: Registry) -> str:
    """Text exposition of every metric in the registry."""
    lines = []
    typed = set()  # one TYPE line per metric family (expfmt requirement)
    for name, metric in sorted(registry.snapshot().items()):
        if isinstance(metric, Histogram):
            # HistogramVec children carry labels in their name
            # (`base{extension_point="..."}`): fold them into each series
            # so the exposition stays valid Prometheus text format.
            base, extra = name, ""
            if "{" in name:
                base, extra = name.split("{", 1)
                extra = extra.rstrip("}") + ","
            if base not in typed:
                typed.add(base)
                lines.append(f"# TYPE {base} histogram")
            acc = 0
            for bound, c in zip(metric.buckets, metric.counts):
                acc += c
                lines.append(f'{base}_bucket{{{extra}le="{bound}"}} {acc}')
            lines.append(f'{base}_bucket{{{extra}le="+Inf"}} {metric.n}')
            suffix = "{" + extra.rstrip(",") + "}" if extra else ""
            lines.append(f"{base}_sum{suffix} {metric.total}")
            lines.append(f"{base}_count{suffix} {metric.n}")
        elif isinstance(metric, (Counter, Gauge)):
            kind = "counter" if isinstance(metric, Counter) else "gauge"
            lines.append(f"# TYPE {name} {kind}")
            with metric._lock:
                items = dict(metric._v)
            if not items:
                lines.append(f"{name} 0")
            for labels, v in sorted(items.items()):
                if labels:
                    lbl = ",".join(
                        f'label{i}="{x}"' for i, x in enumerate(labels)
                    )
                    lines.append(f"{name}{{{lbl}}} {v}")
                else:
                    lines.append(f"{name} {v}")
    return "\n".join(lines) + "\n"


class HealthServer:
    """healthz/readyz/livez + /metrics for one Scheduler."""

    def __init__(self, scheduler, host: str = "127.0.0.1", port: int = 0):
        sched = scheduler

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, body: str, code: int = 200,
                       ctype: str = "text/plain") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:
                if self.path in ("/healthz", "/livez"):
                    self._reply("ok")
                elif self.path == "/readyz":
                    ready = sched.informers.wait_for_sync(0.01)
                    leader = (
                        sched.leader_elector.is_leader()
                        if sched.leader_elector
                        else True
                    )
                    if ready:
                        self._reply(f"ok\nleader: {leader}")
                    else:
                        self._reply("informers not synced", 503)
                elif self.path == "/metrics":
                    self._reply(render_prometheus(sched.metrics))
                elif self.path == "/debug/threads":
                    # the pprof goroutine-dump analogue: every thread's
                    # stack, the first tool out of the bag for a hung
                    # scheduler (component-base wires /debug/pprof the
                    # same way)
                    import sys as _sys
                    import traceback

                    names = {
                        t.ident: t.name for t in threading.enumerate()
                    }
                    lines = []
                    for tid, frame in _sys._current_frames().items():
                        lines.append(
                            f"Thread {names.get(tid, '?')} ({tid}):"
                        )
                        lines.extend(
                            ln.rstrip()
                            for ln in traceback.format_stack(frame)
                        )
                        lines.append("")
                    self._reply("\n".join(lines))
                elif self.path.startswith("/debug/profile"):
                    # sampling profile over a short window (pprof's
                    # /debug/pprof/profile?seconds=N): stacks of EVERY
                    # thread sampled at ~100 Hz and aggregated by frame —
                    # a tracing profiler would only see this handler's
                    # thread
                    import sys as _sys
                    import time as _t
                    from collections import Counter
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    seconds = min(float(q.get("seconds", ["2"])[0]), 30.0)
                    me = threading.get_ident()
                    counts: Counter = Counter()
                    samples = 0
                    deadline = _t.monotonic() + seconds
                    while _t.monotonic() < deadline:
                        for tid, frame in _sys._current_frames().items():
                            if tid == me:
                                continue
                            f = frame
                            while f is not None:
                                co = f.f_code
                                counts[
                                    f"{co.co_filename.rsplit('/', 1)[-1]}"
                                    f":{co.co_name}"
                                ] += 1
                                f = f.f_back
                        samples += 1
                        _t.sleep(0.01)
                    lines = [f"samples: {samples} over {seconds}s"]
                    for frame_id, n in counts.most_common(40):
                        lines.append(f"{n / max(samples, 1):7.2%}  {frame_id}")
                    self._reply("\n".join(lines) + "\n")
                else:
                    self._reply("not found", 404)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "HealthServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="scheduler-health", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
