"""Dynamic resource allocation — device claims, TPU-first.

The reference's DynamicResources plugin (plugins/dynamicresources/
dynamicresources.go:275,1145 — 2,161 LoC of PreEnqueue/PreFilter/
Filter/Reserve/Unreserve/PreBind over ResourceClaim objects) walks
nodes matching claim allocations.  The TPU-native design reuses the two
primitives the rest of scheduling already rides:

  * device CAPACITY is a node-published countable resource
    (`devices/<class>`, api.device_resource) — an UNALLOCATED claim's
    device count folds into the consuming pod's effective requests and
    the NodeResourcesFit kernel does the filtering;
  * an ALLOCATED claim pins its consumers to the allocation's node via
    a hostname selector term riding the static-feasibility bitsets —
    which is how claim SHARING co-locates pods (the DRA property device
    plugins can't express).

Host side (this module): claim/class indexes fed by informers, the
Reserve/Unreserve assume cache, and PreBind allocation writes — the
same protocol shape as scheduler/volumebinding.py.

Accounting model: device usage rides the consuming pods' effective
requests, with one CARRIER per claim (recorded on the claim at
allocation): the carrier's requests include the device count for the
claim's whole lifetime — from its own solve (claim unallocated then)
through cache add and remove — so the node's usage vector stays exact
and symmetric; sharers contribute only the co-location pin.  Reserve
rejects a placement whose node disagrees with an existing allocation
(two sharers solved in one batch re-solve under the pin).

Carrier death with surviving sharers HANDS OFF: on_consumer_delete
promotes a surviving consumer to carrier (claim status write + cache
re-account under the cache lock), so the allocation's devices stay
charged to the node until the LAST consumer is gone — the reference's
allocation-holds-until-deallocate semantics (dynamicresources.go:275).
Consumers are tracked in an O(1) index fed by the scheduler's pod
events, so the delete path no longer lists every pod.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from ..api import store as st
from ..api import types as api

_IMPOSSIBLE = api.NodeSelector(
    terms=[
        api.NodeSelectorTerm(
            match_expressions=[
                api.Requirement(
                    "resource.kubernetes.io/unsatisfiable", api.OP_IN,
                    ["true"],
                )
            ]
        )
    ]
)


def _pin(node_name: str) -> api.NodeSelector:
    return api.NodeSelector(
        terms=[
            api.NodeSelectorTerm(
                match_expressions=[
                    api.Requirement(
                        api.LABEL_HOSTNAME, api.OP_IN, [node_name]
                    )
                ]
            )
        ]
    )


# -- topology-shaped claims ---------------------------------------------------
#
# A claim with spec.topology = "AxBxC" requests a contiguous carve-out
# of one TPU slice instead of `count` loose devices.  The prospective
# carrier solves WITH the shape (pod_shape -> SnapshotBuilder
# pod_shape_hook), so the batched carve-out kernels steer it onto a
# free-box corner; Reserve then records the carve-out anchored at the
# landing node's coordinates and every consumer — carrier and sharers —
# is pinned INSIDE the box by slice/coord label selector terms, which
# the batched static-feasibility filter evaluates like any other
# selector (no host Python on the match path).


def format_carveout(slice_name: str, lo, shape) -> str:
    return (
        f"slice={slice_name};lo={lo[0]},{lo[1]},{lo[2]};"
        f"shape={shape[0]}x{shape[1]}x{shape[2]}"
    )


def parse_carveout(text: str):
    """(slice, (x,y,z), (a,b,c)) or None for empty/malformed."""
    if not text:
        return None
    fields = dict(
        part.split("=", 1) for part in text.split(";") if "=" in part
    )
    lo = api.parse_coords(fields.get("lo"))
    shape = api.parse_topology(fields.get("shape"))
    name = fields.get("slice")
    if not name or lo is None or shape is None:
        return None
    return name, lo, shape


def _pin_carveout(carve) -> api.NodeSelector:
    """Selector pinning a consumer inside a recorded carve-out: slice
    name + the enumerated coordinate strings of the box (the host-side
    expansion into explicit value sets is exactly how every selector
    reaches the device bitsets — ops/schema.py module docstring)."""
    name, (x0, y0, z0), (a, b, c) = carve
    coords = [
        f"{x},{y},{z}"
        for z in range(z0, z0 + c)
        for y in range(y0, y0 + b)
        for x in range(x0, x0 + a)
    ]
    return api.NodeSelector(
        terms=[
            api.NodeSelectorTerm(
                match_expressions=[
                    api.Requirement(api.LABEL_TPU_SLICE, api.OP_IN, [name]),
                    api.Requirement(api.LABEL_TPU_COORDS, api.OP_IN, coords),
                ]
            )
        ]
    )


def _node_slice_info(node: api.Node):
    """(slice, coords) of a node's TPU labels, or None (the host half of
    the ops/schema.py encode semantics — malformed degrades to absent)."""
    labels = node.meta.labels
    name = labels.get(api.LABEL_TPU_SLICE)
    if not name:
        return None
    dims = api.parse_topology(labels.get(api.LABEL_TPU_TOPOLOGY))
    coords = api.parse_coords(labels.get(api.LABEL_TPU_COORDS))
    if dims is None or coords is None:
        return None
    if any(cc >= d for cc, d in zip(coords, dims)):
        return None
    return name, coords


class DeviceClaimBinder:
    """Host-side DRA state + the Reserve/PreBind protocol."""

    def __init__(self, store: st.Store):
        self.store = store
        self._mu = threading.RLock()
        self._claims: Dict[str, api.ResourceClaim] = {}   # ns/name
        self._classes: Dict[str, api.DeviceClass] = {}
        # assume cache: claim key -> (node, carrier pod key) at Reserve
        self._assumed: Dict[str, Tuple[str, str]] = {}
        # assumed carve-outs of topology-shaped claims: claim key ->
        # formatted carveout string (written through at PreBind)
        self._assumed_carve: Dict[str, str] = {}
        # consumer index: claim key -> live consumer pod keys (fed by
        # the scheduler's pod events; replaces O(pods) delete scans)
        self._consumers: Dict[str, set] = {}

    # -- informer handlers -------------------------------------------------

    def on_claim(self, typ: str, claim: api.ResourceClaim, old) -> None:
        key = f"{claim.meta.namespace}/{claim.meta.name}"
        with self._mu:
            if typ == st.DELETED:
                self._claims.pop(key, None)
                self._assumed.pop(key, None)
                self._assumed_carve.pop(key, None)
            else:
                self._claims[key] = claim
                if claim.status.allocated_node:
                    # the written allocation supersedes the assume
                    self._assumed.pop(key, None)
                    self._assumed_carve.pop(key, None)

    def on_class(self, typ: str, dc: api.DeviceClass, old) -> None:
        with self._mu:
            if typ == st.DELETED:
                self._classes.pop(dc.meta.name, None)
            else:
                self._classes[dc.meta.name] = dc

    # -- the pod_transform hook --------------------------------------------

    def _allocation(self, key: str, claim) -> Tuple[str, str]:
        """(node, carrier) for a claim — from written status or the
        assume cache.  Callers hold self._mu."""
        if claim.status.allocated_node:
            return claim.status.allocated_node, claim.status.carrier
        return self._assumed.get(key, ("", ""))

    def _carveout(self, key: str, claim):
        """The claim's recorded carve-out (written status or the assume
        cache), parsed, or None.  Callers hold self._mu."""
        return parse_carveout(
            claim.status.carveout or self._assumed_carve.get(key, "")
        )

    def pod_shape(self, pod: api.Pod):
        """SnapshotBuilder.pod_shape_hook: the carve-out extent the
        pod's FIRST unallocated topology-shaped claim requests, or None.
        Once a carve-out is recorded, consumers pin inside it via the
        box selector instead (pod_requirements) and solve unshaped."""
        with self._mu:
            for claim_name in pod.spec.resource_claims:
                key = f"{pod.meta.namespace}/{claim_name}"
                claim = self._claims.get(key)
                if claim is None or not claim.spec.topology:
                    continue
                node, _carrier = self._allocation(key, claim)
                if node:
                    continue
                shape = api.parse_topology(claim.spec.topology)
                if shape is not None:
                    return shape
        return None

    def pod_requirements(
        self, pod: api.Pod
    ) -> Tuple[Optional[api.NodeSelector], Dict[str, int]]:
        pkey = f"{pod.meta.namespace}/{pod.meta.name}"
        selector: Optional[api.NodeSelector] = None
        requests: Dict[str, int] = {}
        with self._mu:
            for claim_name in pod.spec.resource_claims:
                key = f"{pod.meta.namespace}/{claim_name}"
                claim = self._claims.get(key)
                if claim is None:
                    return _IMPOSSIBLE, {}
                if claim.spec.device_class_name not in self._classes:
                    return _IMPOSSIBLE, {}
                node, carrier = self._allocation(key, claim)
                res = api.device_resource(claim.spec.device_class_name)
                if node:
                    # allocated: every consumer co-locates; the CARRIER
                    # keeps carrying the device count so the node's
                    # usage stays accounted for the claim's lifetime.
                    # A topology-shaped allocation pins consumers INSIDE
                    # the carve-out box (matched in the batched filter)
                    # instead of onto the carrier's single node.
                    carve = self._carveout(key, claim)
                    pin = _pin_carveout(carve) if carve else _pin(node)
                    selector = api.and_selectors(selector, pin)
                    if carrier == pkey:
                        requests[res] = (
                            requests.get(res, 0) + claim.spec.count
                        )
                    continue
                requests[res] = requests.get(res, 0) + claim.spec.count
        return selector, requests

    # -- Reserve / Unreserve / PreBind ------------------------------------

    def reserve(self, pod: api.Pod, node: api.Node) -> bool:
        """Assume allocations for the pod's unallocated claims on the
        chosen node (capacity was already enforced by the fit kernel via
        the synthetic requests).  A claim already allocated/assumed to a
        DIFFERENT node rejects the placement — two sharers solved in one
        batch (both seeing the claim unallocated) would otherwise bind
        to different nodes; the loser re-solves under the pin."""
        pkey = f"{pod.meta.namespace}/{pod.meta.name}"
        with self._mu:
            picked = []

            def rollback():
                for k in picked:
                    self._assumed.pop(k, None)
                    self._assumed_carve.pop(k, None)

            for claim_name in pod.spec.resource_claims:
                key = f"{pod.meta.namespace}/{claim_name}"
                claim = self._claims.get(key)
                if claim is None:
                    rollback()
                    return False
                alloc_node, _carrier = self._allocation(key, claim)
                if alloc_node:
                    carve = self._carveout(key, claim)
                    if carve is not None:
                        # topology-shaped allocation: any node INSIDE
                        # the carve-out is the allocation's home
                        info = _node_slice_info(node)
                        sname, lo, shape = carve
                        inside = (
                            info is not None
                            and info[0] == sname
                            and all(
                                l <= c < l + s
                                for c, l, s in zip(info[1], lo, shape)
                            )
                        )
                        if not inside:
                            rollback()
                            return False
                    elif alloc_node != node.meta.name:
                        rollback()
                        return False
                    continue
                self._assumed[key] = (node.meta.name, pkey)
                if claim.spec.topology:
                    # anchor the carve-out at the carrier's landing
                    # coordinates (the carve-out kernels steered the
                    # shaped solve onto a free-box corner); a claim
                    # landing off-slice degrades to the plain node pin
                    shape = api.parse_topology(claim.spec.topology)
                    info = _node_slice_info(node)
                    if shape is not None and info is not None:
                        self._assumed_carve[key] = format_carveout(
                            info[0], info[1], shape
                        )
                picked.append(key)
            return True

    def unreserve(self, pod: api.Pod) -> None:
        pkey = f"{pod.meta.namespace}/{pod.meta.name}"
        with self._mu:
            for claim_name in pod.spec.resource_claims:
                key = f"{pod.meta.namespace}/{claim_name}"
                if self._assumed.get(key, ("", ""))[1] == pkey:
                    self._assumed.pop(key, None)
                    self._assumed_carve.pop(key, None)

    def prebind(self, pod: api.Pod, node_name: str) -> None:
        """Write assumed allocations through the API (the PreBind claim
        status update, dynamicresources.go:1145)."""
        for claim_name in pod.spec.resource_claims:
            key = f"{pod.meta.namespace}/{claim_name}"
            with self._mu:
                assumed = self._assumed.get(key)
                carve = self._assumed_carve.get(key, "")
            if assumed is None:
                continue
            node, carrier = assumed
            claim = self.store.get(
                "ResourceClaim", claim_name, pod.meta.namespace
            )
            if not claim.status.allocated_node:
                claim.status.allocated_node = node
                claim.status.carrier = carrier
                claim.status.carveout = carve
                claim.status.phase = "Allocated"
                self.store.update(claim)
            # the assume stays until the informer echoes the write back
            # into _claims — dropping it earlier would briefly account
            # the carrier's devices as unallocated again
            with self._mu:
                cached = self._claims.get(key)
                if cached is not None and cached.status.allocated_node:
                    self._assumed.pop(key, None)

    # -- consumer tracking + deallocation ----------------------------------

    def track_pod(self, typ: str, pod: api.Pod) -> None:
        """Maintain the claim→consumers index from the scheduler's pod
        informer events (pods without claims never reach here)."""
        pkey = f"{pod.meta.namespace}/{pod.meta.name}"
        with self._mu:
            for claim_name in pod.spec.resource_claims:
                key = f"{pod.meta.namespace}/{claim_name}"
                if typ == st.DELETED:
                    self._consumers.get(key, set()).discard(pkey)
                else:
                    self._consumers.setdefault(key, set()).add(pkey)

    def on_consumer_delete(self, claim_key: str, deleted_pkey: str,
                           cache=None) -> None:
        """A consumer died.  Last one out deallocates the claim; a dead
        CARRIER with surviving sharers hands its accounting to a
        survivor (claim-status write + cache re-account) so the devices
        stay charged until deallocation (dynamicresources.go:275)."""
        with self._mu:
            claim = self._claims.get(claim_key)
            survivors = set(self._consumers.get(claim_key, ()))
        survivors.discard(deleted_pkey)
        if claim is None or not claim.status.allocated_node:
            return
        if not survivors:
            self._consumers.pop(claim_key, None)
            try:
                fresh = self.store.get(
                    "ResourceClaim", claim.meta.name, claim.meta.namespace
                )
                fresh.status.allocated_node = ""
                fresh.status.carrier = ""
                fresh.status.carveout = ""
                fresh.status.phase = "Pending"
                self.store.update(fresh)
            except (st.NotFound, st.Conflict):
                pass
            return
        if claim.status.carrier != deleted_pkey:
            return  # a sharer died; the carrier still accounts
        self._transfer_carrier(claim, survivors, cache)

    def _transfer_carrier(self, claim, survivors, cache) -> None:
        """Promote a survivor (preferring one bound to the allocation's
        node) to carrier.  Order matters for accounting symmetry: the
        survivor is UN-accounted under the old carrier identity, the
        carrier flips, then it is re-accounted — its usage now includes
        the devices.  An unbound survivor needs no re-account; it will
        account as carrier when it binds."""
        alloc_node = claim.status.allocated_node
        chosen, chosen_pod = None, None
        for pkey in sorted(survivors):
            ns, _, name = pkey.partition("/")
            try:
                p = self.store.get("Pod", name, ns)
            except st.NotFound:
                continue
            if p.spec.node_name == alloc_node:
                chosen, chosen_pod = pkey, p
                break
            if chosen is None:
                chosen, chosen_pod = pkey, p
        if chosen is None:
            return
        key = f"{claim.meta.namespace}/{claim.meta.name}"
        lock = cache.lock if cache is not None else threading.RLock()
        with lock:
            bound_here = (
                cache is not None
                and chosen_pod.spec.node_name == alloc_node
                and cache.state.has_pod(chosen_pod)
            )
            if bound_here:
                cache.state.remove_pod(chosen_pod)  # usage sans devices
            with self._mu:
                cached = self._claims.get(key)
                if cached is not None:
                    cached.status.carrier = chosen
            if bound_here:
                cache.state.add_pod(chosen_pod)     # usage with devices
        try:
            fresh = self.store.get(
                "ResourceClaim", claim.meta.name, claim.meta.namespace
            )
            fresh.status.carrier = chosen
            self.store.update(fresh)
        except (st.NotFound, st.Conflict):
            pass

    def maybe_deallocate(self, claim_key: str) -> None:
        """Back-compat shim for direct callers: consult the consumer
        index (falling back to a store list when the index never saw
        this claim) and deallocate when empty."""
        with self._mu:
            known = claim_key in self._consumers
            survivors = set(self._consumers.get(claim_key, ()))
            claim = self._claims.get(claim_key)
        if claim is None or not claim.status.allocated_node:
            return
        if not known:
            pods, _ = self.store.list("Pod", namespace=claim.meta.namespace)
            survivors = {
                f"{p.meta.namespace}/{p.meta.name}"
                for p in pods
                if claim.meta.name in p.spec.resource_claims
            }
        if survivors:
            return
        try:
            fresh = self.store.get(
                "ResourceClaim", claim.meta.name, claim.meta.namespace
            )
            fresh.status.allocated_node = ""
            fresh.status.carrier = ""
            fresh.status.carveout = ""
            fresh.status.phase = "Pending"
            self.store.update(fresh)
        except (st.NotFound, st.Conflict):
            pass
