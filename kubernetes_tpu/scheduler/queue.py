"""The 3-tier scheduling queue, adapted to batch draining.

Reference: pkg/scheduler/internal/queue/scheduling_queue.go:90-206.
Tiers and transitions are preserved:

  activeQ        heap in queuesort order (priority desc, then arrival —
                 plugins/queuesort/priority_sort.go:52)
  backoffQ       heap by backoff expiry; exponential per-pod backoff
                 (DefaultPodInitialBackoff 1s .. DefaultPodMaxBackoff 10s,
                 apis/config/types.go:72-77)
  unschedulable  map of pods a cycle failed; they leave on cluster events
                 (move_all_to_active_or_backoff — the pre-QueueingHints
                 moveAllToActiveOrBackoffQueue behaviour) or after the
                 flush interval (flushUnschedulablePodsLeftover,
                 scheduling_queue.go DefaultPodMaxInUnschedulablePodsDuration)

The one TPU-shaped change: the hot consumer is `pop_batch`, which drains
up to max_n pods in queuesort order for one batched device solve, instead
of the reference's one-pod Pop (schedule_one.go:66).  Gated pods
(non-empty spec.scheduling_gates) are held outside all three tiers until
their gates clear — the SchedulingGates PreEnqueue plugin
(plugins/schedulinggates/scheduling_gates.go:62).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..analysis import ledger as _ledger
from ..api import types as api
from ..ops import assign as assign_ops

# Event → wake-set (QueueingHints-lite, internal/queue/events.go:25-89
# reduced to the solver's failure stages).  None = wake every reason.
# The payoff: pod churn (AssignedPodDelete at heartbeat rates) never
# wakes pods that failed on node affinity/taints — freeing resources
# cannot fix a static mismatch.
EVENT_WAKES = {
    "NodeAdd": None,
    "NodeUpdate": None,  # labels/taints/capacity can change any stage
    "NodeDelete": None,  # evicted pods re-enter; survivors re-place
    "AssignedPodDelete": {
        assign_ops.REASON_RESOURCES,
        assign_ops.REASON_PORTS,
        assign_ops.REASON_SPREAD,
        assign_ops.REASON_INTERPOD,
        assign_ops.REASON_GANG,
        # freed devices can open a contiguous carve-out
        assign_ops.REASON_SLICE,
    },
    # adding a pod can satisfy AFFINITY-direction inter-pod terms AND
    # raise a spread constraint's global minimum (a new match in the
    # min-count domain lifts every other domain's cap)
    "AssignedPodAdd": {assign_ops.REASON_INTERPOD, assign_ops.REASON_SPREAD},
    "AssignedPodUpdate": {assign_ops.REASON_INTERPOD, assign_ops.REASON_SPREAD},
}


def pod_key(pod: api.Pod) -> str:
    return f"{pod.meta.namespace}/{pod.meta.name}"


def gang_key(pod: api.Pod) -> Optional[str]:
    """The queue's gang identity: "namespace/group", or None for
    ungrouped pods.  Same-named groups in different namespaces are
    distinct gangs (the PodGroup is a namespaced object in the
    reference; CoschedulingPermit quorums are per namespace too)."""
    group = pod.spec.scheduling_group
    return f"{pod.meta.namespace}/{group}" if group else None


class AdaptiveBatchWindow:
    """Load-adaptive accumulation window for ``pop_batch``.

    Two observed signals drive it:

      * arrival rate ``r`` (pods/s) — EWMA over fixed sampling buckets,
        fed by ``SchedulingQueue.add`` on every new pending pod;
      * per-pod pipeline cost ``c`` (s/pod) — EWMAs of solve and commit
        cost per pod, fed by the scheduler's completed cycles/waves.

    Policy: the window plus the processing time of the batch it collects
    must fit the latency SLO — ``w + (r*w)*c <= slo`` gives
    ``w* = slo / (1 + r*c)``.  Sparse arrivals (fewer than ~2 expected
    during ``w*``) make waiting pointless, so the window floors to
    ``min_window``; sustained churn widens it (bigger batches amortize
    encode/solve/commit) up to ``max_window``.  Overload level >= 2 from
    the scheduler's OverloadController pins it at ``max_window``: the
    cheapest load to shed is per-cycle fixed overhead — fewer, fuller
    cycles.  With no signal yet the configured base window applies.
    """

    GUARDED_FIELDS = {
        "_rate": "_lock",
        "_solve_pp": "_lock",
        "_commit_pp": "_lock",
        "_bucket": "_lock",
        "_bucket_start": "_lock",
        "_overload": "_lock",
    }

    _SAMPLE_S = 0.25   # arrival-rate sampling bucket
    _ALPHA = 0.3       # EWMA weight for new samples

    def __init__(
        self,
        base_window: float = 0.05,
        min_window: float = 0.005,
        max_window: float = 0.25,
        slo_seconds: float = 0.5,
        clock=time.monotonic,
    ):
        self._clock = clock
        self.base = base_window
        self.min = min(min_window, max_window)
        self.max = max_window
        self.slo = slo_seconds
        self._lock = threading.Lock()
        self._rate = 0.0        # pods/s EWMA
        self._solve_pp = 0.0    # solve seconds per pod EWMA
        self._commit_pp = 0.0   # commit seconds per pod EWMA
        self._bucket = 0
        self._bucket_start = self._clock()
        self._overload = 0

    def _fold_locked(self) -> None:
        now = self._clock()
        periods = int((now - self._bucket_start) / self._SAMPLE_S)
        if periods <= 0:
            return
        sample = self._bucket / (periods * self._SAMPLE_S)
        for _ in range(min(periods, 50)):  # idle gaps decay toward 0
            self._rate += self._ALPHA * (sample - self._rate)
        self._bucket = 0
        self._bucket_start += periods * self._SAMPLE_S

    def note_arrival(self, n: int = 1) -> None:
        with self._lock:
            self._fold_locked()
            self._bucket += n

    def note_solve(self, pods: int, seconds: float) -> None:
        if pods <= 0:
            return
        with self._lock:
            self._solve_pp += self._ALPHA * (
                max(seconds, 0.0) / pods - self._solve_pp
            )

    def note_commit(self, pods: int, seconds: float) -> None:
        if pods <= 0:
            return
        with self._lock:
            self._commit_pp += self._ALPHA * (
                max(seconds, 0.0) / pods - self._commit_pp
            )

    def set_overload(self, level: int) -> None:
        with self._lock:
            self._overload = level

    def window(self) -> float:
        with self._lock:
            self._fold_locked()
            if self._overload >= 2:
                return self.max
            r = self._rate
            c = self._solve_pp + self._commit_pp
            if r <= 0.0 and c <= 0.0:
                # no signal yet: the configured base window applies
                return min(max(self.base, self.min), self.max)
            w_star = self.slo / (1.0 + r * c)
            if r * w_star < 2.0:
                # sparse arrivals: waiting would not grow the batch
                return self.min
            return min(max(w_star, self.min), self.max)


@dataclass
class QueuedPodInfo:
    """scheduling_queue.go QueuedPodInfo."""

    pod: api.Pod
    timestamp: float = 0.0            # arrival (queuesort tiebreak)
    attempts: int = 0
    initial_attempt_timestamp: float = 0.0
    unschedulable_since: float = 0.0
    gated: bool = False
    # assign.REASON_* from the failing solve; -1 = unknown (always woken)
    unschedulable_reason: int = -1
    # event clock at pop time (in-flight event tracking,
    # scheduling_queue.go inFlightPods/inFlightEvents): events arriving
    # while this pod is mid-cycle are replayed when it comes back
    popped_event_seq: int = 0


class SchedulingQueue:
    # graftlint guarded-by declarations: all three tiers plus the gang
    # and in-flight-event bookkeeping mutate under the queue condition
    # (producer handlers, pop_batch, and the wake paths race otherwise)
    GUARDED_FIELDS = {
        "_active": "_cond",
        "_class_rr": "_cond",
        "_rr_offset": "_cond",
        "_backoff": "_cond",
        "_unschedulable": "_cond",
        "_gated": "_cond",
        "_infos": "_cond",
        "_tier": "_cond",
        "_group_keys": "_cond",
        "_group_size": "_cond",
        "_gang_staged": "_cond",
        "_event_seq": "_cond",
        "_events_log": "_cond",
        "_closed": "_cond",
    }
    # helpers only reached from under `with self._cond:` (the *_locked
    # suffix convention covers the rest)
    LOCKED_METHODS = frozenset(
        {"_push_active", "_push_backoff", "_drop_group_member"}
    )

    def __init__(
        self,
        backoff_base: float = 1.0,
        backoff_max: float = 10.0,
        unschedulable_flush_after: float = 300.0,
        clock=time.monotonic,
        batch_window: float = 0.0,
        window_ctl: Optional[AdaptiveBatchWindow] = None,
    ):
        self._clock = clock
        self._base = backoff_base
        self._max_backoff = backoff_max
        self._flush_after = unschedulable_flush_after
        # bounded accumulation window (seconds): once pop_batch has at
        # least one pod but fewer than max_n, it keeps collecting new
        # arrivals for up to this long before returning, so churn-paced
        # arrivals form real batches instead of near-empty solves.  0
        # preserves the pop-immediately behaviour.  Bounded by the
        # attempt-latency budget: every pod in the batch pays the window
        # as queueing latency.
        self._batch_window = batch_window
        # optional AdaptiveBatchWindow: when present, pop_batch derives
        # its default window from observed arrival rate + cycle cost
        # instead of the fixed value, and add() feeds the rate estimate.
        # Read-only reference (the controller has its own lock).
        self._window_ctl = window_ctl
        self._cond = threading.Condition()
        self._seq = itertools.count()
        # The active tier is split into one queuesort heap PER PROFILE
        # CLASS (pod.spec.scheduler_name): pop_batch serves the classes
        # deficit-round-robin so one hot profile's arrival stream can
        # never starve another profile's lane, and a profile lane can
        # pop only its own class (`profiles=`).  A single-class queue
        # (the default profile) degenerates to exactly the old global
        # heap — pop order is bit-identical.
        self._active: Dict[str, List[tuple]] = {}  # class -> (-prio, ts, seq, key)
        self._class_rr: List[str] = []           # class round-robin order
        self._rr_offset = 0                      # rotation cursor
        self._backoff: List[tuple] = []          # (ready, seq, key)
        self._unschedulable: Dict[str, QueuedPodInfo] = {}
        self._gated: Dict[str, QueuedPodInfo] = {}
        self._infos: Dict[str, QueuedPodInfo] = {}   # all known pending pods
        self._tier: Dict[str, str] = {}          # key -> active|backoff|unsched|gated|gangstage|inflight
        # Gang bookkeeping (the coscheduling PodGroup PreEnqueue pattern):
        # _group_keys tracks every pending member per gang (for atomic
        # draining in pop_batch); _group_size is the gang's declared
        # member count (max over members — one member declaring it is
        # enough); _gang_staged holds members of gangs that have not yet
        # reached that size.  Gangs are keyed "namespace/group"
        # (_gang_of): same-named groups in different namespaces are
        # DISTINCT gangs — pooling them inflated whole-gang counts and,
        # worse, let one namespace's inflight member park another
        # namespace's half-gang in pop_batch's gang pull forever (the
        # per-namespace quorum the CoschedulingPermit r4 fix already
        # established; the store's per-shard fan-out surfaced the queue
        # half of the same bug by skewing cross-namespace pop timing).
        self._group_keys: Dict[str, set] = {}
        self._group_size: Dict[str, int] = {}
        self._gang_staged: Dict[str, QueuedPodInfo] = {}
        # In-flight event log (scheduling_queue.go inFlightEvents): each
        # cluster event gets a sequence number; a pod parked after its
        # cycle replays events that arrived since it was popped — without
        # this, an event landing DURING the cycle that just failed the
        # pod is lost and the pod parks forever (e.g. the PV that makes
        # it schedulable appearing while the solve runs).
        self._event_seq = 0
        self._events_log: deque = deque(maxlen=512)  # (seq, wake-set|None)
        self._closed = False

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _class_of(pod: api.Pod) -> str:
        return pod.spec.scheduler_name or ""

    def _push_active(self, info: QueuedPodInfo) -> None:
        key = pod_key(info.pod)
        cls = self._class_of(info.pod)
        heap = self._active.get(cls)
        if heap is None:
            heap = self._active[cls] = []
            self._class_rr.append(cls)
        heapq.heappush(
            heap,
            (-info.pod.spec.priority, info.timestamp, next(self._seq), key),
        )
        self._tier[key] = "active"
        self._cond.notify_all()

    def _backoff_duration(self, info: QueuedPodInfo) -> float:
        # calculateBackoffDuration: base * 2^(attempts-1), capped
        d = self._base * (2 ** max(info.attempts - 1, 0))
        return min(d, self._max_backoff)

    def _push_backoff(self, info: QueuedPodInfo) -> None:
        key = pod_key(info.pod)
        ready = self._clock() + self._backoff_duration(info)
        heapq.heappush(self._backoff, (ready, next(self._seq), key))
        self._tier[key] = "backoff"
        self._cond.notify_all()

    def _flush_due_locked(self) -> None:
        now = self._clock()
        while self._backoff and self._backoff[0][0] <= now:
            _, _, key = heapq.heappop(self._backoff)
            info = self._infos.get(key)
            if info is not None and self._tier.get(key) == "backoff":
                self._push_active(info)
        # unschedulable flush interval
        stale = [
            k for k, inf in self._unschedulable.items()
            if now - inf.unschedulable_since >= self._flush_after
        ]
        for k in stale:
            info = self._unschedulable.pop(k)
            self._push_backoff(info)

    # -- producer side (event handlers) -----------------------------------

    def add(self, pod: api.Pod) -> None:
        """A new pending pod (eventhandlers addPodToSchedulingQueue)."""
        with self._cond:
            if self._closed:
                return
            key = pod_key(pod)
            now = self._clock()
            info = self._infos.get(key)
            if info is None:
                info = QueuedPodInfo(
                    pod=pod, timestamp=now, initial_attempt_timestamp=now
                )
                self._infos[key] = info
                if self._window_ctl is not None:
                    # new pending pod: one arrival sample for the
                    # adaptive window's rate estimate
                    self._window_ctl.note_arrival()
            info.pod = pod
            if pod.spec.scheduling_gates:
                info.gated = True
                if self._tier.get(key) == "inflight":
                    # re-gated mid-cycle: parking IS the pod's
                    # disposition — the in-flight cycle's later
                    # requeue/park callbacks see the gate and no-op
                    _ledger.discharge("pod", key)
                self._gated[key] = info
                self._tier[key] = "gated"
                return
            info.gated = False
            if self._tier.get(key) in ("active", "backoff", "inflight"):
                return
            self._unschedulable.pop(key, None)
            self._gated.pop(key, None)
            self._admit_locked(info)

    def _admit_locked(self, info: QueuedPodInfo) -> None:
        """Admit an ungated pending pod: register gang membership, stage
        it if its gang is not whole yet (a partial gang must never reach
        a solve), otherwise push to active — releasing any members that
        were staged waiting for it.  Callers hold self._cond."""
        key = pod_key(info.pod)
        group = gang_key(info.pod)
        if group:
            self._group_keys.setdefault(group, set()).add(key)
            declared = info.pod.spec.scheduling_group_size
            if declared:
                self._group_size[group] = max(
                    declared, self._group_size.get(group, 0)
                )
            size = self._group_size.get(group, 0)
            if size and len(self._group_keys[group]) < size:
                self._gang_staged[key] = info
                self._tier[key] = "gangstage"
                return
            self._release_gang_locked(group)
        self._push_active(info)

    def _release_gang_locked(self, group: str) -> None:
        """Release every still-staged member of a gang that is now whole
        (no-op while it is short).  Runs from _admit_locked AND from
        update() — a pod can complete its gang by JOINING via update
        (or a same-group update can newly declare the size); without the
        update-side call the staged members stayed in 'gangstage'
        forever.  Callers hold self._cond."""
        size = self._group_size.get(group, 0)
        keys = self._group_keys.get(group, set())
        if size and len(keys) < size:
            return
        for k in [
            k for k in keys
            if self._tier.get(k) == "gangstage" and k in self._gang_staged
        ]:
            self._push_active(self._gang_staged.pop(k))

    def update(self, pod: api.Pod) -> None:
        """Spec/labels changed: gated pods re-check gates; unschedulable
        pods get another chance (updatePodInSchedulingQueue)."""
        with self._cond:
            key = pod_key(pod)
            info = self._infos.get(key)
            if info is None:
                self.add(pod)
                return
            old_group = gang_key(info.pod)
            new_group = gang_key(pod)
            info.pod = pod
            tier = self._tier.get(key)
            if old_group != new_group:
                # Group membership changed: retract the stale registration
                # (otherwise the old group's whole-gang count stays
                # inflated forever), register under the new group even for
                # pods already queued (pop_batch's gang pull reads
                # _group_keys — an unregistered grouped pod would strand),
                # and re-admit a staged pod under its new spec.
                if old_group and old_group in self._group_keys:
                    self._group_keys[old_group].discard(key)
                    if not self._group_keys[old_group]:
                        self._group_keys.pop(old_group)
                        self._group_size.pop(old_group, None)
                if tier == "gangstage":
                    self._gang_staged.pop(key, None)
                    self._admit_locked(info)
                    return
                if new_group:
                    self._group_keys.setdefault(new_group, set()).add(key)
                    declared = pod.spec.scheduling_group_size
                    if declared:
                        self._group_size[new_group] = max(
                            declared, self._group_size.get(new_group, 0)
                        )
                    # joining may have completed the gang — wake its
                    # staged members (they won't get another event)
                    self._release_gang_locked(new_group)
            elif new_group:
                # same group: a size declaration arriving via update must
                # take effect (first add may have omitted it).  A
                # newly-satisfied size releases the staged members; a
                # newly-SHORT gang re-stages queued members (mirroring
                # delete()) so a partial gang never reaches a solve.
                declared = pod.spec.scheduling_group_size
                if declared:
                    self._group_size[new_group] = max(
                        declared, self._group_size.get(new_group, 0)
                    )
                size = self._group_size.get(new_group, 0)
                if size and len(self._group_keys.get(new_group, ())) < size:
                    for k in list(self._group_keys.get(new_group, ())):
                        if self._tier.get(k) in ("active", "backoff"):
                            inf = self._infos[k]
                            self._gang_staged[k] = inf
                            self._tier[k] = "gangstage"
                else:
                    self._release_gang_locked(new_group)
            if tier == "gated" and not pod.spec.scheduling_gates:
                self._gated.pop(key, None)
                info.gated = False
                self._admit_locked(info)
            elif tier == "unsched":
                self._unschedulable.pop(key, None)
                self._admit_locked(info)

    def delete(self, pod: api.Pod) -> None:
        with self._cond:
            key = pod_key(pod)
            self._infos.pop(key, None)
            self._unschedulable.pop(key, None)
            self._gated.pop(key, None)
            self._gang_staged.pop(key, None)
            if self._tier.pop(key, None) == "inflight":
                _ledger.discharge("pod", key)
            self._drop_group_member(pod, key)
            # lazy heap deletion: stale keys skipped on pop
            group = gang_key(pod)
            if group and group in self._group_keys:
                size = self._group_size.get(group, 0)
                if size and len(self._group_keys[group]) < size:
                    # the gang dropped below its declared size: re-stage
                    # queued members so a partial gang never reaches a
                    # solve (inflight members are left alone — their
                    # batch is already committed)
                    for k in list(self._group_keys[group]):
                        if self._tier.get(k) in ("active", "backoff"):
                            inf = self._infos[k]
                            self._gang_staged[k] = inf
                            self._tier[k] = "gangstage"
            # a departing member can also unblock a skipped gang waiting
            # in pop_batch
            self._cond.notify_all()

    def _drop_group_member(self, pod: api.Pod, key: str) -> None:
        group = gang_key(pod)
        if group and group in self._group_keys:
            self._group_keys[group].discard(key)
            if not self._group_keys[group]:
                del self._group_keys[group]
                self._group_size.pop(group, None)

    # -- consumer side -----------------------------------------------------

    def pop_batch(
        self,
        max_n: int,
        timeout: Optional[float] = None,
        window: Optional[float] = None,
        profiles: Optional[set] = None,
    ) -> List[QueuedPodInfo]:
        """Drain up to max_n pods in queuesort order; blocks until at
        least one is available (or timeout).  Popped pods are 'inflight'
        until done()/requeue.

        Gang-atomic: popping any member of a scheduling group pulls every
        other pending member of that group into the same batch (batch may
        exceed max_n; members in backoff/unschedulable are pulled early —
        gang atomicity dominates their parking), so the joint solve always
        sees whole gangs and its all-or-nothing post-pass can hold.  A
        gang with a member the pop cannot pull (staged below its declared
        size, or inflight in another batch) is skipped whole and returned
        to active.

        `window` (default: the adaptive controller's current window when
        one is wired, else the queue's fixed batch_window) is the bounded
        accumulation window: with at least one pod in hand but fewer than
        max_n, the pop keeps collecting arrivals for up to `window`
        seconds before returning.  Never exceeds `timeout` — a timeout=0
        (non-blocking) pop stays non-blocking.

        `profiles` restricts the pop to those profile classes
        (pod.spec.scheduler_name) — a profile LANE pops only its own
        disjoint pod class.  None pops every class, serving classes
        deficit-round-robin: each rotation takes one pod (or one whole
        gang) per class, so a 10:1 arrival skew between two profiles
        still drains both — one hot class cannot starve another lane's
        pods out of the batch (queuesort order is preserved WITHIN each
        class; a single-class queue pops in exactly the old global
        order)."""
        deadline = None if timeout is None else self._clock() + timeout
        if window is None:
            if self._window_ctl is not None:
                window = self._window_ctl.window()
            else:
                window = self._batch_window
        if timeout is not None:
            window = min(window, timeout)
        pullable = ("active", "backoff", "unsched")
        with self._cond:
            batch: List[QueuedPodInfo] = []

            def take(key: str) -> Optional[QueuedPodInfo]:
                info = self._infos.get(key)
                if info is None or self._tier.get(key) not in pullable:
                    return None  # stale entry
                self._unschedulable.pop(key, None)
                # backoff/active heap entries are lazily skipped via
                # the tier check on their eventual pop
                self._tier[key] = "inflight"
                _ledger.acquire("pod", key)
                info.attempts += 1
                info.popped_event_seq = self._event_seq
                batch.append(info)
                return info

            def take_one(cls: str, skipped: Dict[str, QueuedPodInfo]) -> bool:
                """Take one pod (or one whole gang) from a class heap.
                Returns False when the class has nothing pullable."""
                heap = self._active.get(cls)
                while heap:
                    _, _, _, key = heapq.heappop(heap)
                    info = self._infos.get(key)
                    if (
                        info is None
                        or self._tier.get(key) != "active"
                        or key in skipped
                    ):
                        continue
                    group = gang_key(info.pod)
                    if not group:
                        take(key)
                        return True
                    # the popped key rides along even if registration was
                    # somehow missed — a popped-but-untaken pod would
                    # otherwise strand in tier 'active' with no heap entry
                    members = sorted(self._group_keys.get(group, ()) | {key})
                    if any(
                        self._tier.get(k) not in pullable for k in members
                    ):
                        skipped[key] = info
                        continue
                    for k in members:
                        take(k)
                    return True
                return False

            def collect() -> None:
                skipped: Dict[str, QueuedPodInfo] = {}
                classes = [
                    c for c in self._class_rr
                    if profiles is None or c in profiles
                ]
                n_cls = len(classes)
                if n_cls:
                    # deficit round-robin across profile classes: one
                    # pod (or gang) per class per rotation, starting at
                    # the rotating cursor so successive pops don't
                    # favor the same class's head-of-line
                    start = self._rr_offset % n_cls
                    exhausted: set = set()
                    while len(batch) < max_n and len(exhausted) < n_cls:
                        for j in range(n_cls):
                            cls = classes[(start + j) % n_cls]
                            if cls in exhausted:
                                continue
                            if not take_one(cls, skipped):
                                exhausted.add(cls)
                            if len(batch) >= max_n:
                                break
                    self._rr_offset += 1
                for info in skipped.values():
                    self._push_active(info)

            while True:
                self._flush_due_locked()
                collect()
                if batch:
                    break
                if self._closed:
                    return []
                wait = None
                if self._backoff:
                    wait = max(self._backoff[0][0] - self._clock(), 0.01)
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return []
                    wait = min(wait, remaining) if wait else remaining
                self._cond.wait(wait)
            # bounded accumulation window: wait for more arrivals so
            # churn-paced creates form a real batch (the event-driven
            # batching the reference gets from its queue running ahead
            # of per-pod cycles, scheduling_queue.go:117)
            if window and window > 0 and len(batch) < max_n:
                wend = self._clock() + window
                if deadline is not None:
                    wend = min(wend, deadline)
                while len(batch) < max_n and not self._closed:
                    remaining = wend - self._clock()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                    self._flush_due_locked()
                    collect()
            return batch

    def done(self, pod: api.Pod) -> None:
        """Pod scheduled (assumed+bound): drop from the pending set."""
        with self._cond:
            key = pod_key(pod)
            self._infos.pop(key, None)
            if self._tier.pop(key, None) == "inflight":
                _ledger.discharge("pod", key)
            self._drop_group_member(pod, key)
            # a departing member can unblock a skipped gang in pop_batch
            self._cond.notify_all()

    def add_unschedulable(
        self, info: QueuedPodInfo, reason: int = -1
    ) -> None:
        """A cycle failed to place the pod: park it until an event or the
        flush interval (AddUnschedulableIfNotPresent).  `reason` is the
        solver's failure stage — events wake only plausibly-affected
        pods (move_for_event)."""
        with self._cond:
            key = pod_key(info.pod)
            if key not in self._infos:
                return  # deleted meanwhile
            if self._tier.get(key) == "gated":
                # re-gated mid-cycle (an update added scheduling gates
                # while the pod was inflight): the gate parked it —
                # overriding to "unsched" would let move_for_event
                # requeue a gated pod into a solve
                return
            if self._tier.get(key) == "inflight":
                _ledger.discharge("pod", key)
            info.unschedulable_since = self._clock()
            info.unschedulable_reason = reason
            if self._missed_event_locked(info, reason):
                # an event that can fix this failure arrived while the
                # pod was mid-cycle — retry instead of parking
                self._push_backoff(info)
                return
            self._unschedulable[key] = info
            self._tier[key] = "unsched"

    def _missed_event_locked(self, info: QueuedPodInfo, reason: int) -> bool:
        """True when an event logged after this pod was popped would have
        woken it (the inFlightEvents replay)."""
        if reason == assign_ops.REASON_UNENCODABLE:
            return False
        since = info.popped_event_seq
        if self._events_log and self._events_log[0][0] > since + 1:
            # events between pop and the log's horizon were evicted —
            # be conservative (only happens past 512 events per cycle)
            return True
        for seq, wakes in self._events_log:
            if seq <= since:
                continue
            if wakes is None or reason < 0 or reason in wakes:
                return True
        return False

    def requeue_backoff(self, info: QueuedPodInfo) -> None:
        """Transient failure (e.g. bind error): retry after backoff."""
        with self._cond:
            key = pod_key(info.pod)
            if key not in self._infos:
                return
            if self._tier.get(key) == "gated":
                # re-gated mid-cycle: the gate parked it — pushing to
                # backoff would clobber the gate and pop a gated pod
                # into the next solve
                return
            if self._tier.get(key) == "inflight":
                _ledger.discharge("pod", key)
            self._push_backoff(info)

    def move_all_to_active_or_backoff(self, event: str = "") -> None:
        """A cluster event may have made unschedulable pods schedulable:
        move them to backoff (still inside their backoff window) or
        active (MoveAllToActiveOrBackoffQueue, scheduling_queue.go:117)."""
        self.move_for_event(None)

    def move_for_event(self, event: Optional[str]) -> int:
        """Event-scoped requeue: wake only pods whose recorded failure
        reason the event can plausibly fix (EVENT_WAKES; unknown events
        or reasons wake everything).  Returns the number woken — the
        churn benchmark asserts this stays bounded."""
        wakes = EVENT_WAKES.get(event) if event is not None else None
        moved = 0
        with self._cond:
            self._event_seq += 1
            self._events_log.append((self._event_seq, wakes))
            now = self._clock()
            for key, info in list(self._unschedulable.items()):
                reason = info.unschedulable_reason
                if reason == assign_ops.REASON_UNENCODABLE:
                    # no cluster event can fix a spec the encoder rejects;
                    # only update() (spec change) or the flush interval
                    # revives it — even all-reason events skip it
                    continue
                if wakes is not None and reason >= 0 and reason not in wakes:
                    continue
                self._unschedulable.pop(key)
                moved += 1
                if now < info.unschedulable_since + self._backoff_duration(info):
                    self._push_backoff(info)
                else:
                    self._push_active(info)
        return moved

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._cond:
            active = sum(1 for t in self._tier.values() if t == "active")
            backoff = sum(1 for t in self._tier.values() if t == "backoff")
            return {
                "active": active,
                "backoff": backoff,
                "unschedulable": len(self._unschedulable),
                "gated": len(self._gated),
                "gang_staged": len(self._gang_staged),
                "inflight": sum(
                    1 for t in self._tier.values() if t == "inflight"
                ),
            }

    def pending_count(self) -> int:
        with self._cond:
            return len(self._infos)

    def contains(self, key: str) -> bool:
        """True when the pod is known to the queue in ANY tier (incl.
        gated/staged/inflight) — the leadership-reconciliation sweep
        uses this to find pods a crashed predecessor stranded."""
        with self._cond:
            return key in self._infos

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
