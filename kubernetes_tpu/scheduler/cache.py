"""Scheduler cache: assume/confirm/expire over the incremental tensor
state.

Reference: pkg/scheduler/internal/cache/cache.go:57-260.  The reference
cache keeps per-node NodeInfo structs plus an assumed-pods set with TTL;
ours keeps the same bookkeeping over ops.schema.ClusterState, whose rows
ARE the snapshot (no separate UpdateSnapshot walk — updating a row is
updating the snapshot, the end state the generation protocol exists to
approximate).

Lifecycle (cache.go's state machine):

  assume(pod, node)    solver picked a node; resources land immediately
                       so the next batch sees them (AssumePod)
  finish_binding(pod)  bind API call returned; TTL countdown starts
                       (FinishBinding)
  confirm via add_pod  informer delivered the bound pod: assumed ->
                       confirmed (AddPod on an assumed pod)
  forget(pod)          bind failed; undo the assume (ForgetPod)
  cleanup_expired()    assumed-with-finished-binding pods whose TTL
                       passed are dropped — the informer never confirmed
                       them (cleanupAssumedPods, run periodically)
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..analysis import ledger as _ledger
from ..api import types as api
from ..ops import schema
from .queue import pod_key


@dataclass
class _Assumed:
    pod: api.Pod
    node: str
    binding_finished: bool = False
    deadline: Optional[float] = None


class SchedulerCache:
    # graftlint guarded-by declarations: every access to these fields
    # must hold self._lock (analysis/guarded.py; docs/static_analysis.md)
    GUARDED_FIELDS = {
        "state": "_lock",
        "_assumed": "_lock",
        "_nominated": "_lock",
        "_waiting_on_node": "_lock",
    }
    # reviewed to run with the lock already held (callers acquire it)
    LOCKED_METHODS = frozenset({"_account"})

    def __init__(
        self,
        state: schema.ClusterState,
        ttl: float = 30.0,
        clock=time.monotonic,
    ):
        self.state = state
        self.ttl = ttl
        self._clock = clock
        self._lock = threading.RLock()
        self._assumed: Dict[str, _Assumed] = {}
        # Nominated pods (preemption winners waiting to land): their
        # requests overlay the nominated node's usage in OTHER pods'
        # snapshots, so nobody steals the space their victims freed — the
        # PodNominator / RunFilterPluginsWithNominatedPods analogue
        # (framework/interface.go:778, runtime/framework.go:962).
        self._nominated: Dict[str, tuple] = {}  # key -> (pod, node_name)
        # Pods delivered before their node (informers are per-kind threads
        # with no cross-kind ordering).  The reference cache tolerates this
        # by creating a stub NodeInfo (cache.go AddPod on unknown node);
        # we buffer and apply when the node arrives.
        self._waiting_on_node: Dict[str, Dict[str, api.Pod]] = {}

    @property
    def lock(self) -> threading.RLock:
        """The cache mutex.  The solve path holds it while encoding a
        snapshot from live state (the UpdateSnapshot-under-mutex property,
        cache.go:185) so informer threads can't mutate mid-encode."""
        return self._lock

    # -- nodes (informer-fed) ---------------------------------------------

    def add_node(self, node: api.Node) -> None:
        with self._lock:
            self.state.add_node(node)
            for pod in self._waiting_on_node.pop(node.meta.name, {}).values():
                if not self.state.has_pod(pod):
                    self.state.add_pod(pod)

    def update_node(self, node: api.Node) -> None:
        with self._lock:
            self.state.update_node(node)

    def remove_node(self, name: str) -> None:
        with self._lock:
            # drop assumed entries for pods that lived on the node
            for key, a in list(self._assumed.items()):
                if a.node == name:
                    self._assumed.pop(key)
                    _ledger.discharge("assume", key)
            self._waiting_on_node.pop(name, None)
            self.state.remove_node(name)

    # -- assume protocol ---------------------------------------------------

    def assume(self, pod: api.Pod, node: str) -> None:
        key = pod_key(pod)
        with self._lock:
            if key in self._assumed:
                raise ValueError(f"pod {key} already assumed")
            self.state.add_pod(pod, node)
            self._assumed[key] = _Assumed(pod=pod, node=node)
            _ledger.acquire("assume", key)
            # the pod landed — its nomination's reservation is spent
            self._nominated.pop(key, None)

    # -- nominations (PodNominator) ----------------------------------------

    def nominate(self, pod: api.Pod, node_name: str) -> None:
        with self._lock:
            self._nominated[pod_key(pod)] = (pod, node_name)

    def remove_nomination(self, pod: api.Pod) -> None:
        with self._lock:
            self._nominated.pop(pod_key(pod), None)

    def nominations_excluding(self, keys) -> List[tuple]:
        """(node_name, pod) reservations for nominated pods NOT in `keys`
        (a batch must not see its own members' reservations — a nominee
        schedules INTO its reserved space)."""
        with self._lock:
            return [
                (node, pod)
                for k, (pod, node) in self._nominated.items()
                if k not in keys
            ]

    def finish_binding(self, pod: api.Pod) -> None:
        with self._lock:
            a = self._assumed.get(pod_key(pod))
            if a is not None and not a.binding_finished:
                a.binding_finished = True
                a.deadline = self._clock() + self.ttl

    def finish_binding_all(self, pods: List[api.Pod]) -> None:
        """finish_binding for a whole bind wave under one lock
        acquisition + one clock read (the binding stage commits waves of
        hundreds of pods; per-pod lock churn is measurable there)."""
        with self._lock:
            deadline = self._clock() + self.ttl
            for pod in pods:
                a = self._assumed.get(pod_key(pod))
                if a is not None and not a.binding_finished:
                    a.binding_finished = True
                    a.deadline = deadline

    def forget(self, pod: api.Pod) -> bool:
        """Undo an assume (ForgetPod).  Returns True when an assumed
        entry was actually released — callers use this to fire the
        capacity-freed queue wake only when capacity really came back."""
        key = pod_key(pod)
        with self._lock:
            a = self._assumed.pop(key, None)
            if a is not None:
                _ledger.discharge("assume", key)
                self.state.remove_pod(a.pod)
                return True
            return False

    def is_assumed(self, pod: api.Pod) -> bool:
        with self._lock:
            return pod_key(pod) in self._assumed

    def assumed_nodes(self) -> Dict[str, str]:
        """Snapshot of the assume set: pod key -> assumed node (the
        leadership-reconciliation sweep walks this against the store)."""
        with self._lock:
            return {k: a.node for k, a in self._assumed.items()}

    def forget_key(self, key: str, node: Optional[str] = None) -> bool:
        """forget() by key — with `node`, only when the entry still
        points at that node (a confirm that raced the reconcile sweep
        must win).  Returns True when an entry was released."""
        with self._lock:
            a = self._assumed.get(key)
            if a is None or (node is not None and a.node != node):
                return False
            self._assumed.pop(key)
            _ledger.discharge("assume", key)
            self.state.remove_pod(a.pod)
            return True

    # -- bound pods (informer-fed) ----------------------------------------

    def _account(self, pod: api.Pod) -> None:
        """Add the pod to state, buffering when its node is unknown."""
        try:
            self.state.add_pod(pod)
        except KeyError:
            self._waiting_on_node.setdefault(pod.spec.node_name, {})[
                pod_key(pod)
            ] = pod

    def add_pod(self, pod: api.Pod) -> None:
        """Informer ADDED/MODIFIED with an assigned node.  Confirms an
        assumed pod (dropping its TTL) or accounts a newly seen one."""
        key = pod_key(pod)
        with self._lock:
            a = self._assumed.pop(key, None)
            if a is not None:
                _ledger.discharge("assume", key)
                if a.node == pod.spec.node_name:
                    return  # confirmed; resources already accounted
                # scheduled elsewhere than assumed: re-account
                self.state.remove_pod(a.pod)
            if not self.state.has_pod(pod):
                self._account(pod)

    def update_pod(self, old: api.Pod, new: api.Pod) -> None:
        """Bound-pod spec change (in-place resize, label edits): swap the
        accounted object so requested rows and constraint tables track the
        new spec (cache.go UpdatePod)."""
        key = pod_key(new)
        if old.spec == new.spec and old.meta.labels == new.meta.labels:
            # status-only update (phase/conditions churn): nothing the
            # accounting or constraint tables read changed — skip the
            # O(pods-on-node) re-account entirely
            return
        with self._lock:
            if self._assumed.get(key) is not None:
                # still assumed: add_pod's confirm path owns the transition
                self.add_pod(new)
                return
            for waiting in self._waiting_on_node.values():
                waiting.pop(key, None)
            if self.state.has_pod(old):
                self.state.remove_pod(old)
            if new.spec.node_name:
                self._account(new)

    def remove_pod(self, pod: api.Pod) -> None:
        key = pod_key(pod)
        with self._lock:
            if self._assumed.pop(key, None) is not None:
                _ledger.discharge("assume", key)
            for waiting in self._waiting_on_node.values():
                waiting.pop(key, None)
            if self.state.has_pod(pod):
                self.state.remove_pod(pod)

    # -- expiry ------------------------------------------------------------

    def cleanup_expired(self) -> List[api.Pod]:
        """Drop assumed pods whose binding finished but the informer never
        confirmed within TTL.  Returns the expired pods (callers requeue
        them)."""
        now = self._clock()
        expired: List[api.Pod] = []
        with self._lock:
            for key, a in list(self._assumed.items()):
                if a.binding_finished and a.deadline is not None and now > a.deadline:
                    self._assumed.pop(key)
                    _ledger.discharge("assume", key)
                    self.state.remove_pod(a.pod)
                    expired.append(a.pod)
        return expired

    def assumed_count(self) -> int:
        with self._lock:
            return len(self._assumed)
