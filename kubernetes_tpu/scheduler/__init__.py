"""scheduler layer (being built out; see package docstring for the layout map)."""
