"""Host-side scheduler framework (SURVEY.md layer 8, pkg/scheduler):
3-tier scheduling queue, assume-TTL cache over the incremental tensor
state, metrics registry, and the informer-fed run loop that drains the
queue into batched TPU solves."""

from .cache import SchedulerCache
from .metrics import Registry
from .queue import QueuedPodInfo, SchedulingQueue, pod_key
from .scheduler import Scheduler

__all__ = [
    "Scheduler", "SchedulerCache", "SchedulingQueue", "QueuedPodInfo",
    "Registry", "pod_key",
]
