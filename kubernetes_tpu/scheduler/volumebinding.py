"""VolumeBinding — the storage-topology scheduling family, TPU-first.

The reference's VolumeBinding plugin (pkg/scheduler/framework/plugins/
volumebinding/volume_binding.go:69,248 — PreFilter/Filter/Reserve/
PreBind over an assume cache, 2,119 LoC) walks every node in Filter and
re-matches PVs against claims per node.  The TPU-native design moves the
whole per-node feasibility question INTO the existing tensor pipeline
instead of adding a new device kernel:

  * a bound PVC's PV carries a NodeSelector (VolumeNodeAffinity) — that
    IS a required node selector, so it is ANDed into the pod's effective
    selector and rides the static-feasibility bitset kernels;
  * an unbound PVC's eligible PVs form an OR over their node
    affinities — exactly a NodeSelector's OR-of-AND term list;
  * WaitForFirstConsumer dynamic provisioning contributes the storage
    class's allowedTopologies as another OR term;
  * CSI attach limits are node-published countable resources
    (`attachable-volumes-<driver>`, mirroring nodevolumelimits/csi.go) —
    they ride the NodeResourcesFit kernel as scalar resources.

So Filter costs nothing new on device; this module is the HOST half:
claim/volume indexing, the per-pod requirement derivation
(SnapshotBuilder.pod_transform), Reserve/Unreserve with an assume cache
(util/assumecache/assume_cache.go), and PreBind API writes.

A claim that cannot be satisfied at all (missing PVC, no candidate PV
and no provisioner) yields an IMPOSSIBLE selector — the pod solves to
unschedulable with the static-failure reason and PV/PVC cluster events
requeue it (the UnschedulableAndUnresolvable analogue).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..api import store as st
from ..api import types as api

# a label key no node can carry: ANDing this into a selector makes it
# statically infeasible everywhere
_IMPOSSIBLE = api.NodeSelector(
    terms=[
        api.NodeSelectorTerm(
            match_expressions=[
                api.Requirement(
                    "volume.kubernetes.io/unsatisfiable", api.OP_IN, ["true"]
                )
            ]
        )
    ]
)


and_selectors = api.and_selectors  # canonical definition: api.types


def _host_pin(node_name: str) -> api.NodeSelector:
    return api.NodeSelector(terms=[
        api.NodeSelectorTerm(match_expressions=[
            api.Requirement(api.LABEL_HOSTNAME, api.OP_IN, [node_name])
        ])
    ])


class VolumeBinder:
    """Host-side volume state + the Reserve/PreBind protocol.

    Thread model: informer handlers mutate the indexes under self._mu;
    pod_requirements runs under the scheduler cache lock during encode
    (single scheduling thread), reserve/prebind/unreserve run on the
    scheduling thread only.
    """

    def __init__(self, store: st.Store):
        self.store = store
        self._mu = threading.RLock()
        self._pvs: Dict[str, api.PersistentVolume] = {}
        # claimRef -> pv name, for O(1) half-bound crash repair
        self._claimref_index: Dict[str, str] = {}
        self._pvcs: Dict[str, api.PersistentVolumeClaim] = {}  # ns/name
        self._classes: Dict[str, api.StorageClass] = {}
        # assume cache (util/assumecache): pv name -> claim key it is
        # reserved for, and claim key -> (pv name | None for provision)
        self._assumed_pv: Dict[str, str] = {}
        self._assumed_claim: Dict[str, Optional[str]] = {}
        # drivers with at least one node publishing an attach limit —
        # absent limit means unlimited (nodevolumelimits: no CSINode
        # entry, no cap), so attach requests are only emitted for
        # limited drivers
        self._limited_drivers: set = set()
        # claim key -> {pod key: node}: bound consumers per claim (the
        # VolumeRestrictions multi-attach input)
        self._claim_consumers: Dict[str, Dict[str, str]] = {}

    # -- informer handlers -------------------------------------------------

    def on_pv(self, typ: str, pv: api.PersistentVolume, old) -> None:
        with self._mu:
            if typ == st.DELETED:
                gone = self._pvs.pop(pv.meta.name, None)
                if gone is not None and gone.spec.claim_ref:
                    self._claimref_index.pop(gone.spec.claim_ref, None)
            else:
                prev = self._pvs.get(pv.meta.name)
                if (
                    prev is not None
                    and prev.spec.claim_ref
                    and prev.spec.claim_ref != pv.spec.claim_ref
                ):
                    self._claimref_index.pop(prev.spec.claim_ref, None)
                self._pvs[pv.meta.name] = pv
                if pv.spec.claim_ref:
                    self._claimref_index[pv.spec.claim_ref] = pv.meta.name

    def on_pvc(self, typ: str, pvc: api.PersistentVolumeClaim, old) -> None:
        key = f"{pvc.meta.namespace}/{pvc.meta.name}"
        with self._mu:
            if typ == st.DELETED:
                self._pvcs.pop(key, None)
            else:
                self._pvcs[key] = pvc

    def on_class(self, typ: str, sc: api.StorageClass, old) -> None:
        with self._mu:
            if typ == st.DELETED:
                self._classes.pop(sc.meta.name, None)
            else:
                self._classes[sc.meta.name] = sc

    def on_node(self, typ: str, node: api.Node, old) -> None:
        with self._mu:
            for key in node.status.allocatable:
                if key.startswith(api.ATTACH_LIMIT_PREFIX):
                    self._limited_drivers.add(
                        key[len(api.ATTACH_LIMIT_PREFIX):]
                    )

    def on_pod(self, typ: str, pod: api.Pod, old) -> None:
        """Track which node each claim's BOUND consumers run on — the
        VolumeRestrictions multi-attach input
        (plugins/volumerestrictions/volume_restrictions.go:306): a
        ReadWriteOnce volume in use on node X forces later consumers to
        co-locate on X."""
        claims = [
            v.persistent_volume_claim
            for v in pod.spec.volumes
            if v.persistent_volume_claim
        ]
        if not claims:
            return
        pkey = f"{pod.meta.namespace}/{pod.meta.name}"
        with self._mu:
            for claim in claims:
                key = f"{pod.meta.namespace}/{claim}"
                consumers = self._claim_consumers.setdefault(key, {})
                if (
                    typ == st.DELETED
                    or not pod.spec.node_name
                    # terminal pods release the attachment — an evicted
                    # consumer must not pin replacements to its node
                    or pod.status.phase in ("Succeeded", "Failed")
                ):
                    consumers.pop(pkey, None)
                    if not consumers:
                        self._claim_consumers.pop(key, None)
                else:
                    consumers[pkey] = pod.spec.node_name

    # -- the pod_transform hook (encode-time requirement derivation) -------

    def pod_requirements(
        self, pod: api.Pod
    ) -> Tuple[Optional[api.NodeSelector], Dict[str, int]]:
        """(extra required selector, extra scalar requests) for the pod's
        PVC-backed volumes — the PreFilter analogue, folded into the
        snapshot encode so the device Filter pass needs no volume
        kernel."""
        selector: Optional[api.NodeSelector] = None
        attach: Dict[str, int] = {}
        with self._mu:
            for vol in pod.spec.volumes:
                claim = vol.persistent_volume_claim
                if not claim:
                    continue
                key = f"{pod.meta.namespace}/{claim}"
                pvc = self._pvcs.get(key)
                if pvc is None:
                    return _IMPOSSIBLE, {}  # claim object missing
                sel, driver = self._claim_constraint(key, pvc)
                if sel is _IMPOSSIBLE:
                    return _IMPOSSIBLE, {}
                selector = and_selectors(selector, sel)
                if driver and driver in self._limited_drivers:
                    res = api.attach_limit_resource(driver)
                    attach[res] = attach.get(res, 0) + 1
        return selector, attach

    def _claim_constraint(
        self, key: str, pvc: api.PersistentVolumeClaim
    ) -> Tuple[Optional[api.NodeSelector], str]:
        """One claim's node constraint + its attach-limit driver.

        Driver note: for an UNBOUND claim the attach-limit driver is
        taken from the first eligible PV (falling back to the class
        provisioner), assuming one driver per storage class — the
        overwhelmingly common deployment shape, and what the class's
        provisioner field implies.  Mixed-driver PVs under one class
        could charge the attach count to the wrong
        `attachable-volumes-<driver>` scalar until Reserve picks the
        concrete PV (documented divergence)."""
        bound_pv = pvc.spec.volume_name or self._assumed_claim.get(key)
        if bound_pv:
            pv = self._pvs.get(bound_pv)
            if pv is None:
                return _IMPOSSIBLE, ""  # bound to a vanished volume
            sel = pv.spec.node_affinity
            if set(pv.spec.access_modes) == {"ReadWriteOnce"}:
                # multi-attach restriction: an RWO volume mounts on ONE
                # node — consumers co-locate with the current attachment
                nodes = set(self._claim_consumers.get(key, {}).values())
                if len(nodes) == 1:
                    sel = api.and_selectors(sel, _host_pin(next(iter(nodes))))
            return sel, pv.spec.driver
        if key in self._assumed_claim:  # assumed for provisioning
            return None, ""
        # Crash repair (the PV controller's syncVolume half,
        # pkg/controller/volume/persistentvolume/pv_controller.go): a
        # PV whose claimRef already points at this PVC means a prebind
        # wrote the PV side and died before the PVC write — finish the
        # PVC side and treat the pair as bound, instead of skipping the
        # PV (claimRef set) and resolving the claim IMPOSSIBLE forever.
        # O(1): the claimRef index is maintained by the PV informer.
        ref_pv = self._claimref_index.get(key)
        if ref_pv is not None:
            pv = self._pvs.get(ref_pv)
            if pv is not None:
                self._finish_half_bound(key, pvc, pv.meta.name)
                return pv.spec.node_affinity, pv.spec.driver
        # unbound: OR over eligible PVs' affinities; a PV without a node
        # affinity is mountable anywhere -> the claim is unconstrained
        candidates = self._eligible_pvs(pvc)
        sc = self._classes.get(pvc.spec.storage_class_name)
        terms: List[api.NodeSelectorTerm] = []
        unconstrained = False
        driver = ""
        for pv in candidates:
            driver = driver or pv.spec.driver
            if pv.spec.node_affinity is None:
                unconstrained = True
            else:
                terms.extend(pv.spec.node_affinity.terms)
        if sc is not None and sc.provisioner:
            driver = driver or sc.provisioner
            if sc.allowed_topologies is None:
                unconstrained = True
            else:
                terms.extend(sc.allowed_topologies.terms)
        if unconstrained:
            return None, driver
        if not terms:
            return _IMPOSSIBLE, ""  # no PV fits and nothing can provision
        return api.NodeSelector(terms=terms), driver

    def _finish_half_bound(
        self, key: str, pvc: api.PersistentVolumeClaim, pv_name: str
    ) -> None:
        """Complete the PVC side of a half-written binding (journal
        replay after a crash between prebind's two writes)."""
        try:
            fresh = self.store.get(
                "PersistentVolumeClaim", pvc.meta.name, pvc.meta.namespace
            )
            if not fresh.spec.volume_name:
                fresh.spec.volume_name = pv_name
                fresh.status.phase = api.PVC_BOUND
                self.store.update(fresh)
            # local cache: don't wait for the informer echo
            pvc.spec.volume_name = pv_name
        except Exception:
            # best-effort; the informer-driven next pass retries
            pvc.spec.volume_name = pv_name

    def _eligible_pvs(
        self, pvc: api.PersistentVolumeClaim
    ) -> List[api.PersistentVolume]:
        """Available volumes matching class, access modes, and size
        (volumebinding binder.go findMatchingVolumes)."""
        want_modes = set(pvc.spec.access_modes)
        out = []
        for pv in self._pvs.values():
            if pv.spec.claim_ref or pv.meta.name in self._assumed_pv:
                continue
            if pv.status.phase != api.PV_AVAILABLE:
                continue
            if pv.spec.storage_class_name != pvc.spec.storage_class_name:
                continue
            if not want_modes.issubset(set(pv.spec.access_modes)):
                continue
            if pv.storage() < pvc.requested_storage():
                continue
            out.append(pv)
        return out

    # -- Reserve / Unreserve / PreBind ------------------------------------

    def reserve(self, pod: api.Pod, node: api.Node) -> bool:
        """Pick concrete volumes for the pod's unbound claims on the
        chosen node and assume the bindings (Reserve,
        volume_binding.go:369).  Returns False when no eligible volume
        fits the node — the placement is rejected and the pod retries."""
        with self._mu:
            picked: List[Tuple[str, Optional[str]]] = []
            for vol in pod.spec.volumes:
                claim = vol.persistent_volume_claim
                if not claim:
                    continue
                key = f"{pod.meta.namespace}/{claim}"
                pvc = self._pvcs.get(key)
                if pvc is None:
                    self._rollback(picked)
                    return False
                if pvc.spec.volume_name or key in self._assumed_claim:
                    continue  # already bound/assumed
                pv = self._pick_pv(pvc, node)
                if pv is not None:
                    self._assumed_pv[pv.meta.name] = key
                    self._assumed_claim[key] = pv.meta.name
                    picked.append((key, pv.meta.name))
                    continue
                sc = self._classes.get(pvc.spec.storage_class_name)
                if sc is not None and sc.provisioner and (
                    sc.allowed_topologies is None
                    or _selector_matches(sc.allowed_topologies, node)
                ):
                    # dynamic provisioning deferred to PreBind
                    self._assumed_claim[key] = None
                    picked.append((key, None))
                    continue
                self._rollback(picked)
                return False
            return True

    def _pick_pv(
        self, pvc: api.PersistentVolumeClaim, node: api.Node
    ) -> Optional[api.PersistentVolume]:
        """Smallest sufficient topology-compatible volume
        (binder.go FindBestMatchVolume)."""
        best = None
        for pv in self._eligible_pvs(pvc):
            if pv.spec.node_affinity is not None and not _selector_matches(
                pv.spec.node_affinity, node
            ):
                continue
            if best is None or pv.storage() < best.storage():
                best = pv
        return best

    def unreserve(self, pod: api.Pod) -> None:
        """Roll back this pod's assumed bindings (Unreserve — bind
        failed or a later plugin rejected the placement)."""
        with self._mu:
            for vol in pod.spec.volumes:
                claim = vol.persistent_volume_claim
                if not claim:
                    continue
                key = f"{pod.meta.namespace}/{claim}"
                pv_name = self._assumed_claim.pop(key, None)
                if pv_name:
                    self._assumed_pv.pop(pv_name, None)

    def _rollback(self, picked: List[Tuple[str, Optional[str]]]) -> None:
        for key, pv_name in picked:
            self._assumed_claim.pop(key, None)
            if pv_name:
                self._assumed_pv.pop(pv_name, None)

    def prebind(self, pod: api.Pod, node_name: str) -> None:
        """Write the assumed bindings through the API (PreBind,
        volume_binding.go:248: BindPodVolumes).  Dynamic provisioning is
        satisfied in-process: the control plane provisions a PV pinned
        to the chosen node's topology (the integration-test PV
        controller's role; real clusters have an external provisioner)."""
        node = None
        for vol in pod.spec.volumes:
            claim = vol.persistent_volume_claim
            if not claim:
                continue
            key = f"{pod.meta.namespace}/{claim}"
            with self._mu:
                pv_name = self._assumed_claim.get(key)
            if key not in self._assumed_claim and pv_name is None:
                continue  # already bound earlier
            pvc = self.store.get(
                "PersistentVolumeClaim", claim, pod.meta.namespace
            )
            if pvc.spec.volume_name:
                continue
            if pv_name is None:
                if node is None:
                    node = self.store.get("Node", node_name, namespace="")
                pv = self._provision(pvc, node)
                pv_name = pv.meta.name
            pv = self.store.get("PersistentVolume", pv_name)
            pv.spec.claim_ref = key
            pv.spec.claim_uid = pvc.meta.uid
            pv.status.phase = api.PV_BOUND
            self.store.update(pv)
            pvc.spec.volume_name = pv_name
            pvc.status.phase = api.PVC_BOUND
            self.store.update(pvc)
            with self._mu:
                self._assumed_claim.pop(key, None)
                self._assumed_pv.pop(pv_name, None)

    def _provision(
        self, pvc: api.PersistentVolumeClaim, node: api.Node
    ) -> api.PersistentVolume:
        sc = self._classes.get(pvc.spec.storage_class_name)
        topo_val = node.meta.labels.get(api.LABEL_ZONE)
        affinity = None
        if topo_val is not None:
            affinity = api.NodeSelector(
                terms=[
                    api.NodeSelectorTerm(
                        match_expressions=[
                            api.Requirement(
                                api.LABEL_ZONE, api.OP_IN, [topo_val]
                            )
                        ]
                    )
                ]
            )
        pv = api.PersistentVolume(
            meta=api.ObjectMeta(
                name=f"pvc-{pvc.meta.namespace}-{pvc.meta.name}"
            ),
            spec=api.PersistentVolumeSpec(
                capacity={api.STORAGE: pvc.requested_storage()},
                access_modes=list(pvc.spec.access_modes),
                storage_class_name=pvc.spec.storage_class_name,
                node_affinity=affinity,
                driver=sc.provisioner if sc else "",
            ),
        )
        self.store.create(pv)
        return pv


def _selector_matches(sel: api.NodeSelector, node: api.Node) -> bool:
    """Host-side OR-of-AND selector evaluation against one node."""
    return sel.matches(node.meta.labels)
