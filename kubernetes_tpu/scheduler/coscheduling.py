"""Coscheduling at Permit — gangs held in the waiting-pods map.

The out-of-tree coscheduling plugin's real mechanism: each gang member
returns Wait at Permit; when the last member arrives, the plugin walks
the waiting map and Allows the whole group; a timeout rejects the
stragglers and the group retries.  Our queue already stages gangs
pre-solve (SchedulingQueue gang staging) — this plugin is the
alternative hold point for groups whose size is declared out-of-band
(no scheduling_group_size on the pods), and the proof that the Permit
seam carries the protocol real plugins need.

Usage (sizes from PodGroup API objects — the real plugin's shape):
    from ..api import crd
    crd.install_podgroup_crd(store)
    store.create(crd.pod_group("my-gang", min_member=4))
    cos = CoschedulingPermit(
        scheduler.waiting, directory=crd.PodGroupDirectory(store)
    )
    for fwk in scheduler.profiles:
        fwk.register("permit", cos.permit)

Usage (out-of-band dict, kept for tests/embedding):
    cos = CoschedulingPermit(scheduler.waiting, sizes={"my-gang": 4})

Release is quorum-of-currently-waiting: a member that times out and
requeues re-enters Permit on its retry, so stale arrivals can never
release a partial gang.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..api import types as api
from .waitingpods import WaitingPodsMap

DEFAULT_PERMIT_TIMEOUT = 30.0


class CoschedulingPermit:
    def __init__(
        self,
        waiting: WaitingPodsMap,
        sizes: Optional[Dict[str, int]] = None,
        timeout: float = DEFAULT_PERMIT_TIMEOUT,
        directory=None,  # api.crd.PodGroupDirectory: sizes from PodGroups
    ):
        self.waiting = waiting
        self.sizes = dict(sizes or {})
        self.timeout = timeout
        self.directory = directory
        self._lock = threading.Lock()

    def _size_of(self, pod: api.Pod) -> Optional[int]:
        g = pod.spec.scheduling_group
        if g is None:
            return None
        if g in self.sizes:
            return self.sizes[g]
        if self.directory is not None:
            return self.directory.size_for(pod.meta.namespace, g)
        return None

    def _timeout_of(self, pod: api.Pod) -> float:
        if self.directory is not None:
            t = self.directory.timeout_for(
                pod.meta.namespace, pod.spec.scheduling_group
            )
            if t:
                return float(t)
        return self.timeout

    def group_of(self, pod: api.Pod) -> Optional[str]:
        g = pod.spec.scheduling_group
        return g if self._size_of(pod) is not None else None

    def _waiting_members(self, namespace: str, group: str):
        """Members of (namespace, group) CURRENTLY parked at Permit.
        Release decisions read the live waiting map, never an arrival
        history — a member that timed out and was requeued must not
        count toward the quorum (it will re-enter Permit on retry), and
        same-named gangs in different namespaces must not pool."""
        return [
            wp for wp in self.waiting.iterate()
            if wp.pod.spec.scheduling_group == group
            and wp.pod.meta.namespace == namespace
        ]

    def permit(self, pod: api.Pod, node: str):
        """The Permit plugin callable: Wait until the declared member
        count is simultaneously parked at Permit, then Allow the whole
        group (this pod itself returns allow — it never enters the
        map).  Release is two-phase (WaitingPod.try_claim then allow):
        a member timing out between the quorum snapshot and the release
        makes its claim fail, the claims roll back, and this pod waits —
        a partial gang can never be allowed."""
        group = pod.spec.scheduling_group
        if group is None:
            return "allow", 0.0
        # ONE size lookup: the directory reads live API objects, and a
        # PodGroup deleted between two lookups must not surface as a
        # TypeError mid-Permit
        size = self._size_of(pod)
        if size is None:
            return "allow", 0.0
        timeout = self._timeout_of(pod)
        with self._lock:
            parked = self._waiting_members(pod.meta.namespace, group)
            if len(parked) + 1 < size:
                return "wait", timeout
            claimed = [wp for wp in parked if wp.try_claim()]
            if len(claimed) + 1 < size:
                for wp in claimed:
                    wp.release_claim()
                return "wait", timeout
            for wp in claimed:
                wp.allow()
            return "allow", 0.0
