"""Coscheduling at Permit — gangs held in the waiting-pods map.

The out-of-tree coscheduling plugin's real mechanism: each gang member
returns Wait at Permit; when the last member arrives, the plugin walks
the waiting map and Allows the whole group; a timeout rejects the
stragglers and the group retries.  Our queue already stages gangs
pre-solve (SchedulingQueue gang staging) — this plugin is the
alternative hold point for groups whose size is declared out-of-band
(no scheduling_group_size on the pods), and the proof that the Permit
seam carries the protocol real plugins need.

Usage (sizes from PodGroup API objects — the real plugin's shape):
    from ..api import crd
    crd.install_podgroup_crd(store)
    store.create(crd.pod_group("my-gang", min_member=4))
    cos = CoschedulingPermit(
        scheduler.waiting, directory=crd.PodGroupDirectory(store)
    )
    for fwk in scheduler.profiles:
        fwk.register("permit", cos.permit)

Usage (out-of-band dict, kept for tests/embedding):
    cos = CoschedulingPermit(scheduler.waiting, sizes={"my-gang": 4})

Release is quorum-of-currently-waiting: a member that times out and
requeues re-enters Permit on its retry, so stale arrivals can never
release a partial gang.

Slice carve-out preference (docs/scheduler_loop.md "TPU slice
topology"): with a `node_lookup` wired, the release point additionally
checks whether the gang's placements realize a contiguous carve-out —
one slice, pairwise-distinct coordinates, bounding-box volume equal to
the member count.  `carveout="prefer"` only counts the outcome
(gang_contiguous_placements_total / slice_carveout_fallbacks_total
when a metrics registry is given); `carveout="require"` REJECTS a
non-contiguous gang instead of allowing it — every member requeues and
re-solves (the solver's require-mode filter then steers the retry onto
a contiguous sub-cuboid), so a fragmented release can never bind.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..api import types as api
from .waitingpods import WaitingPodsMap

DEFAULT_PERMIT_TIMEOUT = 30.0


def carveout_contiguous(nodes) -> bool:
    """True when the node set realizes a contiguous carve-out: every
    node slice-labelled, one slice, pairwise-distinct coordinates, and
    the axis-aligned bounding box exactly filled (volume == count) —
    the host-policy half of the ops/slices.py semantics contract."""
    infos = []
    for node in nodes:
        if node is None:
            return False
        labels = node.meta.labels
        name = labels.get(api.LABEL_TPU_SLICE)
        coords = api.parse_coords(labels.get(api.LABEL_TPU_COORDS))
        if not name or coords is None:
            return False
        infos.append((name, coords))
    if not infos:
        return False
    if len({name for name, _ in infos}) != 1:
        return False
    coords = [c for _, c in infos]
    if len(set(coords)) != len(coords):
        return False
    vol = 1
    for axis in range(3):
        vals = [c[axis] for c in coords]
        vol *= max(vals) - min(vals) + 1
    return vol == len(coords)


class CoschedulingPermit:
    def __init__(
        self,
        waiting: WaitingPodsMap,
        sizes: Optional[Dict[str, int]] = None,
        timeout: float = DEFAULT_PERMIT_TIMEOUT,
        directory=None,  # api.crd.PodGroupDirectory: sizes from PodGroups
        carveout: str = "prefer",   # prefer | require | off
        node_lookup=None,           # name -> api.Node, for carve-out checks
        metrics=None,               # scheduler.metrics.Registry (optional)
    ):
        self.waiting = waiting
        self.sizes = dict(sizes or {})
        self.timeout = timeout
        self.directory = directory
        if carveout not in ("prefer", "require", "off"):
            raise ValueError(
                f"carveout must be prefer|require|off, got {carveout!r}"
            )
        self.carveout = carveout
        self.node_lookup = node_lookup
        self.metrics = metrics
        self._lock = threading.Lock()

    def _gang_shaped(self, pods) -> bool:
        return any(api.parse_topology(p.spec.tpu_topology) for p in pods)

    def _check_carveout(self, pods, node_names) -> Optional[bool]:
        """None = not applicable (policy off / unshaped gang / no node
        lookup); else whether the placements realize a carve-out."""
        if self.carveout == "off" or self.node_lookup is None:
            return None
        if not self._gang_shaped(pods):
            return None
        return carveout_contiguous(
            [self.node_lookup(name) for name in node_names]
        )

    def _size_of(self, pod: api.Pod) -> Optional[int]:
        g = pod.spec.scheduling_group
        if g is None:
            return None
        if g in self.sizes:
            return self.sizes[g]
        if self.directory is not None:
            return self.directory.size_for(pod.meta.namespace, g)
        return None

    def _timeout_of(self, pod: api.Pod) -> float:
        if self.directory is not None:
            t = self.directory.timeout_for(
                pod.meta.namespace, pod.spec.scheduling_group
            )
            if t:
                return float(t)
        return self.timeout

    def group_of(self, pod: api.Pod) -> Optional[str]:
        g = pod.spec.scheduling_group
        return g if self._size_of(pod) is not None else None

    def _waiting_members(self, namespace: str, group: str):
        """Members of (namespace, group) CURRENTLY parked at Permit.
        Release decisions read the live waiting map, never an arrival
        history — a member that timed out and was requeued must not
        count toward the quorum (it will re-enter Permit on retry), and
        same-named gangs in different namespaces must not pool."""
        return [
            wp for wp in self.waiting.iterate()
            if wp.pod.spec.scheduling_group == group
            and wp.pod.meta.namespace == namespace
        ]

    def permit(self, pod: api.Pod, node: str):
        """The Permit plugin callable: Wait until the declared member
        count is simultaneously parked at Permit, then Allow the whole
        group (this pod itself returns allow — it never enters the
        map).  Release is two-phase (WaitingPod.try_claim then allow):
        a member timing out between the quorum snapshot and the release
        makes its claim fail, the claims roll back, and this pod waits —
        a partial gang can never be allowed."""
        group = pod.spec.scheduling_group
        if group is None:
            return "allow", 0.0
        # ONE size lookup: the directory reads live API objects, and a
        # PodGroup deleted between two lookups must not surface as a
        # TypeError mid-Permit
        size = self._size_of(pod)
        if size is None:
            return "allow", 0.0
        timeout = self._timeout_of(pod)
        with self._lock:
            parked = self._waiting_members(pod.meta.namespace, group)
            if len(parked) + 1 < size:
                return "wait", timeout
            claimed = [wp for wp in parked if wp.try_claim()]
            if len(claimed) + 1 < size:
                for wp in claimed:
                    wp.release_claim()
                return "wait", timeout
            # carve-out check at the release point: the whole gang's
            # placements are known only here
            contiguous = self._check_carveout(
                [wp.pod for wp in claimed] + [pod],
                [wp.node for wp in claimed] + [node],
            )
            if contiguous is not None and self.metrics is not None:
                if contiguous:
                    self.metrics.gang_contiguous_placements.inc()
                else:
                    self.metrics.slice_carveout_fallbacks.inc()
            if contiguous is False and self.carveout == "require":
                # reject instead of binding a fragmented gang: claims
                # roll back first (reject defers to a held claim), then
                # every member requeues and re-solves under the
                # require-mode carve-out filter
                for wp in claimed:
                    wp.release_claim()
                for wp in claimed:
                    wp.reject("slice carve-out not contiguous")
                return "reject", 0.0
            for wp in claimed:
                wp.allow()
            return "allow", 0.0
