"""Coscheduling at Permit — gangs held in the waiting-pods map.

The out-of-tree coscheduling plugin's real mechanism: each gang member
returns Wait at Permit; when the last member arrives, the plugin walks
the waiting map and Allows the whole group; a timeout rejects the
stragglers and the group retries.  Our queue already stages gangs
pre-solve (SchedulingQueue gang staging) — this plugin is the
alternative hold point for groups whose size is declared out-of-band
(no scheduling_group_size on the pods), and the proof that the Permit
seam carries the protocol real plugins need.

Usage:
    cos = CoschedulingPermit(scheduler.waiting, sizes={"my-gang": 4})
    for fwk in scheduler.profiles:
        fwk.register("permit", cos.permit)

Release is quorum-of-currently-waiting: a member that times out and
requeues re-enters Permit on its retry, so stale arrivals can never
release a partial gang.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..api import types as api
from .waitingpods import WaitingPodsMap

DEFAULT_PERMIT_TIMEOUT = 30.0


class CoschedulingPermit:
    def __init__(
        self,
        waiting: WaitingPodsMap,
        sizes: Optional[Dict[str, int]] = None,
        timeout: float = DEFAULT_PERMIT_TIMEOUT,
    ):
        self.waiting = waiting
        self.sizes = dict(sizes or {})
        self.timeout = timeout
        self._lock = threading.Lock()

    def group_of(self, pod: api.Pod) -> Optional[str]:
        g = pod.spec.scheduling_group
        return g if g in self.sizes else None

    def _waiting_members(self, namespace: str, group: str):
        """Members of (namespace, group) CURRENTLY parked at Permit.
        Release decisions read the live waiting map, never an arrival
        history — a member that timed out and was requeued must not
        count toward the quorum (it will re-enter Permit on retry), and
        same-named gangs in different namespaces must not pool."""
        return [
            wp for wp in self.waiting.iterate()
            if wp.pod.spec.scheduling_group == group
            and wp.pod.meta.namespace == namespace
        ]

    def permit(self, pod: api.Pod, node: str):
        """The Permit plugin callable: Wait until the declared member
        count is simultaneously parked at Permit, then Allow the whole
        group (this pod itself returns allow — it never enters the
        map).  Release is two-phase (WaitingPod.try_claim then allow):
        a member timing out between the quorum snapshot and the release
        makes its claim fail, the claims roll back, and this pod waits —
        a partial gang can never be allowed."""
        group = self.group_of(pod)
        if group is None:
            return "allow", 0.0
        with self._lock:
            parked = self._waiting_members(pod.meta.namespace, group)
            if len(parked) + 1 < self.sizes[group]:
                return "wait", self.timeout
            claimed = [wp for wp in parked if wp.try_claim()]
            if len(claimed) + 1 < self.sizes[group]:
                for wp in claimed:
                    wp.release_claim()
                return "wait", self.timeout
            for wp in claimed:
                wp.allow()
            return "allow", 0.0
