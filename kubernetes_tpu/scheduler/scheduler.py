"""The host scheduler: informer-fed cache + queue draining into batched
device solves, with a two-stage solve/bind pipeline.

Reference mapping (pkg/scheduler/scheduler.go, schedule_one.go):

  Scheduler.run            scheduler.go:438 Run (queue flush + hot loop)
  schedule_batch           the batched schedule_one.go:66 ScheduleOne:
                           NextPod -> schedulePod -> assume; one device
                           dispatch schedules the whole batch.  The bind
                           tail is handed to the binding stage as a WAVE
                           and commits off-thread.
  binding stage            schedule_one.go:118's `go bindingCycle` —
                           binds never run on the scheduling thread.
                           Ours is a dedicated worker committing whole
                           waves through one store transaction
                           (store.update_wave) instead of per-pod
                           goroutines doing per-pod POSTs; assume-cache
                           entries bridge the gap exactly as the
                           reference's assume/bind split does, so batch
                           N+1's snapshot is correct while batch N's
                           binds are still in flight.
  failure handling         handleSchedulingFailure :1017 ->
                           AddUnschedulableIfNotPresent; a bind error
                           splits that pod out of the wave, forgets the
                           assume and requeues with backoff
  event wiring             eventhandlers.go:287 addAllEventHandlers:
                           informers feed cache (assigned pods, nodes)
                           and queue (pending pods, requeue-on-event)

The scheduling algorithm itself — filters, scores, selectHost, the
assume bookkeeping between pods of one batch — runs on the TPU inside
TPUBatchScheduler (models/batch_scheduler.py).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import numpy as np

from ..analysis import epochs as _epochs
from ..analysis import ledger as _ledger
from ..analysis import retrace as _retrace
from ..api import store as st
from ..api import types as api
from ..client.events import EventRecorder
from ..client.informers import InformerFactory
from ..models.batch_scheduler import TPUBatchScheduler
from ..ops import assign as assign_ops
from ..testing import faults
from ..utils.trace import Trace
from .cache import SchedulerCache
from .config import SchedulerConfiguration
from .framework import Framework, FrameworkRegistry
from .metrics import Registry
from .preemption import PreemptionEvaluator
from .queue import AdaptiveBatchWindow, QueuedPodInfo, SchedulingQueue, pod_key
from .waitingpods import WaitingPod, WaitingPodsMap


class OverloadController:
    """Load-aware degradation ladder for the solve stage.

    Tracks an EWMA of the cycle's PLACEMENT work (pop → solve → stage →
    dispatch) against the latency SLO and exposes a shed level consumed
    each cycle.  The PostFilter preemption pass is EXCLUDED from the
    fed duration: shedding decisions must not be driven by the work
    they shed — counting the pass made one expensive preemption round
    trip the ladder to level 2, which deferred preemption, which left
    no cycles to decay the average: preemption froze exactly when the
    backlog needed it (the self-inhibition bench c9 exposed).

      0  healthy — full work;
      1  overloaded (ewma > slo) — background work sheds first: the
         PostFilter preemption BATCH is capped (the batched dry-run
         amortized the per-pod marginal cost, so an overloaded cycle
         keeps a small batch instead of deferring preemption outright —
         preemption load spikes exactly when the cluster is overloaded);
         pods past the cap count into scheduler_overload_shed_total,
         never the placement work itself;
      2  severe (ewma > 2*slo) — preemption dry-runs defer entirely and
         the adaptive batch window pins at its max: fewer, fuller
         cycles shed per-cycle fixed overhead without dropping pods.

    Levels fall only when the EWMA drops below 80% of the rising
    threshold (hysteresis), so one fast cycle doesn't flap the ladder.
    """

    GUARDED_FIELDS = {"_ewma": "_lock", "_level": "_lock"}

    _ALPHA = 0.3

    def __init__(self, slo_seconds: float = 0.5):
        self.slo = slo_seconds
        self._lock = threading.Lock()
        self._ewma = 0.0
        self._level = 0

    def note_cycle(self, duration_s: float) -> int:
        with self._lock:
            self._ewma += self._ALPHA * (max(duration_s, 0.0) - self._ewma)
            e, lvl = self._ewma, self._level
            if e > 2 * self.slo:
                lvl = 2
            else:
                if lvl == 2 and e < 0.8 * 2 * self.slo:
                    lvl = 1
                if e > self.slo:
                    lvl = max(lvl, 1)
                elif lvl == 1 and e < 0.8 * self.slo:
                    lvl = 0
            self._level = lvl
            return lvl

    def level(self) -> int:
        with self._lock:
            return self._level


def _combine_transforms(transforms):
    """Compose pod_transform hooks: selectors AND together, extra
    requests sum (VolumeBinding + DRA both fold into the encode)."""

    def combined(pod):
        selector, requests = None, {}
        for fn in transforms:
            sel, extra = fn(pod)
            selector = api.and_selectors(selector, sel)
            for k, v in (extra or {}).items():
                requests[k] = requests.get(k, 0) + v
        return selector, requests

    return combined


class _Cycle:
    """One in-flight solve-stage cycle: popped-batch staging state plus
    (optionally) the last profile group still out on the device as a
    DeviceSolve future (scheduler._run's readback pipeline).

    `batch` is every popped info and `handled` the keys a terminal path
    has taken ownership of (staged into the wave, parked, requeued,
    handed to a Permit thread): a cycle that dies mid-flight is salvaged
    by requeueing batch − handled, so a fault can never strand pods in
    the 'inflight' tier (Scheduler._salvage_cycle)."""

    __slots__ = ("stats", "trace", "reservations", "failed", "wave",
                 "pending", "solved_any", "batch", "handled",
                 "spec_token", "mirror_points", "partials_points")

    def __init__(self, stats, trace, reservations, batch):
        self.stats = stats
        self.trace = trace
        self.reservations = reservations
        self.failed: List[QueuedPodInfo] = []
        self.wave: List[tuple] = []
        self.pending = None  # (fwk, sched_name, group, DeviceSolve, t_solve)
        self.solved_any = False
        self.batch: List[QueuedPodInfo] = batch
        self.handled: set = set()
        # speculative dispatch: the wave-failure generation this cycle's
        # solves were dispatched under (None = not speculative), plus
        # per-profile mirror AND partials-cache bookmarks for the
        # invalidation rollback (the two resident buffers roll together)
        self.spec_token = None
        self.mirror_points: Dict[str, tuple] = {}
        self.partials_points: Dict[str, tuple] = {}


_REASON_TEXT = {
    assign_ops.REASON_STATIC: "node affinity/taints/name mismatch",
    assign_ops.REASON_RESOURCES: "insufficient resources",
    assign_ops.REASON_PORTS: "host port conflict",
    assign_ops.REASON_SPREAD: "topology spread constraints violated",
    assign_ops.REASON_INTERPOD: "inter-pod (anti-)affinity rules",
    assign_ops.REASON_GANG: "gang not fully placeable",
    assign_ops.REASON_SLICE: "no free contiguous slice carve-out",
}


class Scheduler:
    # graftlint guarded-by declarations: the binding-stage backlog and
    # worker flags share the wave condition; the device-solve interval
    # log (pipeline-overlap attribution) shares the solve lock
    GUARDED_FIELDS = {
        "_waves": "_wave_cv",
        "_wave_active": "_wave_cv",
        "_binder_stop": "_wave_cv",
        "_stream_inflight": "_wave_cv",
        "_solve_windows": "_solve_lock",
        "_solve_open": "_solve_lock",
        "_wave_fail_gen": "_spec_lock",
        "_inflight_cycles": "_inflight_lock",
    }

    def __init__(
        self,
        store: st.Store,
        batch_size: Optional[int] = None,
        tpu: Optional[TPUBatchScheduler] = None,
        assume_ttl: Optional[float] = None,
        clock=time.monotonic,
        leader_elector=None,
        config: Optional[SchedulerConfiguration] = None,
    ):
        self.store = store
        self.config = (config or SchedulerConfiguration()).validate()
        self.batch_size = batch_size or self.config.batch_size
        # profiles: scheduler_name -> Framework, one shared cluster state
        # (profile/profile.go:46; explicit `tpu` keeps the single-profile
        # constructor shape tests/benches use)
        self.profiles = FrameworkRegistry(
            self.config, state=tpu.state if tpu else None
        )
        if tpu is not None:
            # the injected instance IS the default profile's solver —
            # sharing only its state would silently drop a custom
            # mode/score_config/limits on the scheduling path (the
            # registry-built instance would solve instead)
            self.profiles.default.tpu = tpu
        self.tpu = tpu or self.profiles.default.tpu
        self.cache = SchedulerCache(
            self.tpu.state,
            ttl=assume_ttl or self.config.assume_ttl_seconds,
            clock=clock,
        )
        # overload protection (docs/robustness.md): the adaptive window
        # sizes pop_batch's accumulation from observed arrival rate and
        # solve/commit cost; the overload controller sheds background
        # work (preemption dry-runs) and widens the window when cycles
        # overrun the latency SLO, instead of letting traces pile up
        self.window_ctl: Optional[AdaptiveBatchWindow] = None
        if self.config.adaptive_batch_window:
            self.window_ctl = AdaptiveBatchWindow(
                base_window=self.config.batch_window_seconds,
                min_window=self.config.batch_window_min_seconds,
                max_window=self.config.batch_window_max_seconds,
                slo_seconds=self.config.batch_latency_slo_seconds,
                clock=clock,
            )
        self.overload = OverloadController(
            slo_seconds=self.config.batch_latency_slo_seconds
        )
        self.queue = SchedulingQueue(
            backoff_base=self.config.pod_initial_backoff_seconds,
            backoff_max=self.config.pod_max_backoff_seconds,
            unschedulable_flush_after=self.config.unschedulable_flush_seconds,
            clock=clock,
            batch_window=self.config.batch_window_seconds,
            window_ctl=self.window_ctl,
        )
        self.metrics = Registry()
        # pods parked at Permit (waiting_pods_map.go); coscheduling-style
        # plugins Allow/Reject through this map
        self.waiting = WaitingPodsMap()
        # async: a bind wave must not pay per-pod synchronous Event
        # writes on the scheduling thread (the broadcaster channel)
        self.events = EventRecorder(
            store, component="default-scheduler", async_mode=True
        )
        self.preemption = PreemptionEvaluator(
            self.tpu, self.cache, store, self.metrics
        )
        self.preemption.events = self.events
        # PostFilter budget per cycle: preemption is the exceptional path;
        # cap the per-batch dry-run work so a mass of unschedulable pods
        # can't stall the hot loop.
        self.max_preemptions_per_cycle = self.config.max_preemptions_per_cycle
        # VolumeBinding: host-side claim/volume state; topology + attach
        # limits fold into the snapshot encode via the builder transform
        # (scheduler/volumebinding.py) — PreFilter/Filter cost nothing
        # extra on device.  Reserve rides filter_result, rollback rides
        # unreserve, API writes ride pre_bind.
        from .deviceclaims import DeviceClaimBinder
        from .volumebinding import VolumeBinder

        gate = self.profiles.gate
        self.preemption.pdb_aware = gate.enabled("PDBAwarePreemption")
        self.volumes = VolumeBinder(store)
        self.devices = DeviceClaimBinder(store)
        transforms = []
        if gate.enabled("VolumeBinding"):
            transforms.append(self.volumes.pod_requirements)
        if gate.enabled("DynamicResourceAllocation"):
            transforms.append(self.devices.pod_requirements)
            # topology-shaped claims hand their carve-out extent to the
            # encoder (the batched carve-out kernels steer the carrier
            # onto a free-box corner; scheduler/deviceclaims.py)
            self.tpu.builder.pod_shape_hook = self.devices.pod_shape
        if transforms:
            self.tpu.builder.pod_transform = _combine_transforms(transforms)
        # default plugins on every profile: preemption (PostFilter) +
        # volume binding + device claims (Reserve/Unreserve/PreBind)
        for fwk in self.profiles:
            fwk.metrics = self.metrics
            # background prewarm compiles report into the same histogram
            # as synchronous first-shape compiles
            pool = getattr(fwk.tpu, "prewarm_pool", None)
            if pool is not None:
                pool.compile_observer = (
                    self.metrics.solve_compile_duration.observe
                )
            fwk.post_filter.append(self._preempt_plugin)
            if gate.enabled("VolumeBinding"):
                fwk.filter_result.append(self._volume_reserve_plugin)
                fwk.unreserve.append(self.volumes.unreserve)
                fwk.pre_bind.append(self.volumes.prebind)
            if gate.enabled("DynamicResourceAllocation"):
                fwk.filter_result.append(self._device_reserve_plugin)
                fwk.unreserve.append(self.devices.unreserve)
                fwk.pre_bind.append(self.devices.prebind)
        self.informers = InformerFactory(store)
        # Optional client.leaderelection.LeaderElector: when set, the hot
        # loop only schedules while leading (app/server.go:170-180 —
        # replicated schedulers, single active) — standbys keep informers
        # warm so takeover is immediate.
        self.leader_elector = leader_elector
        # Leadership/restart reconciliation (docs/robustness.md): the
        # flag starts SET so the first leading pass of the hot loop
        # reconciles local pipeline state against the store — covering
        # process restart AND an elector that acquired before this
        # scheduler attached; every later acquisition re-sets it.  The
        # reconcile itself runs on the scheduling thread (never the
        # elector thread, whose renew cadence it must not delay).
        self._reconcile_needed = threading.Event()
        self._reconcile_needed.set()
        if leader_elector is not None:
            prev_cb = leader_elector.on_started_leading

            def _on_started_leading():
                self._reconcile_needed.set()
                if prev_cb:
                    prev_cb()

            leader_elector.on_started_leading = _on_started_leading
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # -- pipelined multi-lane scheduling ------------------------------
        # each lane runs its own pop→encode→solve pipeline over its
        # profiles' disjoint pod classes (docs/scheduler_loop.md); lane 0
        # is the LEAD lane (leadership reconcile, assume-TTL sweeps,
        # cross-cutting metric mirrors).  scheduler_lanes=0 auto-sizes to
        # one lane per profile; a single profile keeps the serial loop.
        names = list(self.profiles.frameworks)
        lanes_cfg = self.config.scheduler_lanes
        n_lanes = len(names) if lanes_cfg == 0 else min(lanes_cfg, len(names))
        n_lanes = max(n_lanes, 1)
        if n_lanes > 1:
            self._lane_profiles: List[Optional[set]] = [
                set(names[i::n_lanes]) for i in range(n_lanes)
            ]
        else:
            self._lane_profiles = [None]  # one lane pops every class
        self._lane_threads: List[threading.Thread] = []
        self.metrics.lane_count.set(float(n_lanes))
        # per-scheduling-thread in-flight cycle (lanes + direct
        # schedule_batch callers salvage their OWN cycle on faults)
        self._inflight_lock = threading.Lock()
        self._inflight_cycles: Dict[int, "_Cycle"] = {}
        # speculative solve overlap: batches dispatched while a wave is
        # still committing record the wave-failure generation; a commit
        # failure/fence bumps it and invalidates the speculation
        self._speculation_enabled = self.config.speculative_solve
        self._spec_lock = threading.Lock()
        self._wave_fail_gen = 0
        # PostFilter preemption shares one evaluator: concurrent lanes
        # serialize their passes (preemption is background work)
        self._postfilter_lock = threading.Lock()
        # -- binding stage (the async binding cycle) ----------------------
        # schedule_batch stages placements (assume + Permit) and hands the
        # bind tail to this worker as a wave; the next cycle's pop/solve
        # overlaps the commit.  Backlog is bounded so a commit stage that
        # falls behind backpressures the solve stage instead of growing
        # an unbounded requeue-latency tail.
        self._waves: deque = deque()  # (entries, attempts) pairs
        self._wave_cv = threading.Condition()
        self._wave_active = False
        self._binder_stop = False
        self._max_wave_backlog = 2
        # device-solve intervals, for the pipeline-overlap metric (the
        # binder reads them to attribute its commit time)
        self._solve_lock = threading.Lock()
        self._solve_windows: deque = deque(maxlen=64)  # (start, end)
        self._solve_open: Optional[float] = None
        # sharded-store commit fan-out: the binder partitions each wave
        # into per-store-shard sub-waves and commits up to this many
        # concurrently (shard A's journal fsync / watch fan-out overlaps
        # shard B's and the next solve).  A 1-shard store keeps the
        # serial single-transaction path and pays for no pool.
        subwave_width = min(
            self.config.commit_subwave_concurrency,
            getattr(store, "shard_count", 1),
        )
        self._commit_pool: Optional[ThreadPoolExecutor] = (
            ThreadPoolExecutor(
                max_workers=subwave_width,
                thread_name_prefix="commit-subwave",
            )
            if subwave_width > 1
            else None
        )
        self._subwave_width = subwave_width
        # streamed sub-wave commits: staging hands each store shard's
        # slice of a wave to the commit pool AS IT STAGES, instead of
        # dispatching the whole wave after the full readback; bounded by
        # 2x the pool width (backpressure on the solve stage)
        self._stream_enabled = (
            self.config.stream_subwaves and self._commit_pool is not None
        )
        self._stream_inflight = 0
        self._bind_thread = threading.Thread(
            target=self._bind_worker, name="bind-wave", daemon=True
        )
        self._bind_thread.start()
        self._wire_handlers()

    # -- event wiring (eventhandlers.go:287) ------------------------------

    def _wire_handlers(self) -> None:
        self.informers.informer("Node").add_handler(self._on_node)
        self.informers.informer("Pod").add_handler(self._on_pod)
        self.informers.informer("Node").add_handler(self.volumes.on_node)
        self.informers.informer("Pod").add_handler(self.volumes.on_pod)
        for kind, handler in (
            ("PersistentVolume", self.volumes.on_pv),
            ("PersistentVolumeClaim", self.volumes.on_pvc),
            ("StorageClass", self.volumes.on_class),
            ("ResourceClaim", self.devices.on_claim),
            ("DeviceClass", self.devices.on_class),
        ):
            inf = self.informers.informer(kind)
            inf.add_handler(handler)
            inf.add_handler(self._on_volume_event)

    def _on_volume_event(self, typ: str, obj, old) -> None:
        # a PV/PVC/StorageClass change can lift a volume-topology static
        # failure (the selector the transform folded in) or free attach
        # capacity — wake statically-parked and resource-parked pods
        self.queue.move_for_event("NodeUpdate")

    def _on_node(self, typ: str, node: api.Node, old) -> None:
        if typ == st.ADDED:
            self.cache.add_node(node)
            self.queue.move_for_event("NodeAdd")
        elif typ == st.MODIFIED:
            self.cache.update_node(node)
            self.queue.move_for_event("NodeUpdate")
        elif typ == st.DELETED:
            self.cache.remove_node(node.meta.name)

    def _on_pod(self, typ: str, pod: api.Pod, old) -> None:
        assigned = bool(pod.spec.node_name)
        if pod.spec.resource_claims and typ != st.DELETED:
            self.devices.track_pod(typ, pod)
        if typ == st.DELETED:
            if assigned:
                # the cache removal must see the claim state the pod was
                # ACCOUNTED under — deallocating first would make
                # remove_pod subtract device counts that were never
                # added (unaccounting symmetry)
                self.cache.remove_pod(pod)
                # a terminated pod frees resources: unschedulable pods
                # may fit now — but only resource/port/spread/interpod
                # failures can benefit (AssignedPodDelete wake set)
                self.queue.move_for_event("AssignedPodDelete")
            else:
                self.queue.delete(pod)
                self.cache.remove_nomination(pod)
            if pod.spec.resource_claims:
                self.devices.track_pod(typ, pod)
                pkey = pod_key(pod)
                for claim_name in pod.spec.resource_claims:
                    # last consumer gone -> deallocate; dead CARRIER with
                    # sharers -> hand accounting to a survivor — AFTER
                    # unaccounting (dynamicresources.go:275 semantics)
                    self.devices.on_consumer_delete(
                        f"{pod.meta.namespace}/{claim_name}",
                        pkey,
                        cache=self.cache,
                    )
            return
        if assigned:
            # bound (or our own bind echoing back): confirm in cache
            if old is not None and not old.spec.node_name:
                self.queue.done(pod)
            if (
                typ == st.MODIFIED
                and old is not None
                and old.spec.node_name == pod.spec.node_name
            ):
                # already-bound pod changed (in-place resize, label edit):
                # re-account so requested rows track the new spec
                self.cache.update_pod(old, pod)
                self.queue.move_for_event("AssignedPodUpdate")
            else:
                self.cache.add_pod(pod)
                # a newly bound pod can satisfy waiting affinity/spread
                # constraints (AssignedPodAdd cluster event)
                self.queue.move_for_event("AssignedPodAdd")
            return
        if self.profiles.for_pod(pod) is None:
            return  # another scheduler's pod (skipPodSchedule)
        fwk = self.profiles.for_pod(pod)
        reason = fwk.run_pre_enqueue(pod)
        if reason:
            # PreEnqueue rejection: stay out of the queue until the next
            # pod UPDATE re-runs the gate (schedulinggates semantics)
            self.queue.delete(pod)
            return
        if typ == st.ADDED:
            self.queue.add(pod)
        else:
            self.queue.update(pod)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Start informers + the scheduling loop thread."""
        self.informers.informer("Node").start()
        self.informers.informer("Pod").start()
        self.informers.informer("PersistentVolume").start()
        self.informers.informer("PersistentVolumeClaim").start()
        self.informers.informer("StorageClass").start()
        self.informers.informer("ResourceClaim").start()
        self.informers.informer("DeviceClass").start()
        self.informers.wait_for_sync()
        self._thread = threading.Thread(
            target=self._run, args=(0,), name="scheduler", daemon=True
        )
        self._thread.start()
        # additional profile lanes (multi-profile configs): each pops
        # and solves its own pod classes concurrently
        self._lane_threads = [
            threading.Thread(
                target=self._run, args=(i,), name=f"scheduler-lane{i}",
                daemon=True,
            )
            for i in range(1, len(self._lane_profiles))
        ]
        for t in self._lane_threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
        if self._thread:
            # a device solve mid-compile can run tens of seconds; tearing
            # the interpreter down under an XLA compile aborts the process,
            # so wait the compile out
            self._thread.join(timeout=120)
        for t in self._lane_threads:
            t.join(timeout=120)
        # drain the binding stage: staged placements are assumed in the
        # cache, so dropping their waves would leak phantom usage until
        # the assume TTL fires
        self.flush_binds(timeout=30)
        with self._wave_cv:
            self._binder_stop = True
            self._wave_cv.notify_all()
        self._bind_thread.join(timeout=10)
        if self._commit_pool is not None:
            self._commit_pool.shutdown(wait=True)
        self.informers.stop()
        self.events.stop()

    def kill(self) -> None:
        """Ungraceful teardown — the chaos harness's process-death
        analogue.  Nothing drains: staged bind waves are dropped on the
        floor and assumed pods are abandoned, exactly what a SIGKILL'd
        scheduler leaves behind (the successor's reconciliation and the
        store's durable state are what recover them).  Never use outside
        crash-restart tests; stop() is the graceful path."""
        # a SIGKILL takes the in-memory obligation ledger with it: the
        # popped/assumed state this instance held is recovered by TTL
        # expiry and successor reconciliation, not discharged
        _ledger.abandon()
        self._stop.set()
        self.queue.close()
        with self._wave_cv:
            self._binder_stop = True
            self._waves.clear()
            self._wave_cv.notify_all()
        if self._thread:
            self._thread.join(timeout=10)
        for t in self._lane_threads:
            t.join(timeout=10)
        self._bind_thread.join(timeout=5)
        if self._commit_pool is not None:
            self._commit_pool.shutdown(wait=False)
        self.informers.stop()
        self.events.stop()

    # -- leadership / restart reconciliation -------------------------------

    def _reconcile_leadership(self) -> None:
        """Make local pipeline state agree with the STORE before the
        first post-acquisition dispatch (on_started_leading's analogue
        of the reference's WaitForCacheSync + queue flush).  A new
        leader — fresh process after a crash, or a warm standby taking
        over — must not trust caches built under someone else's
        leadership:

          * every assumed entry is checked against the store: a pod the
            predecessor (or this process, pre-crash) assumed but never
            durably committed is forgotten and re-queued; a pod the
            store says landed elsewhere is forgotten (the informer
            re-accounts it); a matching bind is kept for the informer to
            confirm;
          * unbound pods missing from the queue entirely (an informer
            gap across the handoff) are swept from the store into it —
            the no-pod-lost floor does not depend on event delivery
            across a leadership boundary;
          * the device mirror is invalidated (next solve performs a full
            RESHARDED re-upload — the delta protocol's resident copy
            belongs to the predecessor's generation history) and the
            solve breaker resets to closed (the cooldown belonged to the
            predecessor's device, not ours).

        Bound-exactly-once across the boundary needs no work here: the
        store is the source of truth, bound pods arrive through the
        informer as bound (never queued), and the wave mutator + write
        fencing reject any late commit that disagrees."""
        log = logging.getLogger(__name__)
        requeued = 0
        try:
            pods, _ = self.store.list("Pod")
        except Exception:  # noqa: BLE001 — retry next cycle
            log.exception("leadership reconcile: store list failed")
            self._reconcile_needed.set()
            return
        by_key = {pod_key(p): p for p in pods}
        for key, node in self.cache.assumed_nodes().items():
            cur = by_key.get(key)
            if cur is not None and cur.spec.node_name == node:
                continue  # durably bound where assumed; informer confirms
            self.cache.forget_key(key, node)
            if cur is not None and not cur.spec.node_name:
                # assumed but never committed: give it back to the queue
                self.queue.add(cur)
                requeued += 1
        # store sweep: unbound pods the queue does not know (popped by a
        # crashed predecessor, or an event lost across the handoff)
        for key, pod in by_key.items():
            if pod.spec.node_name or self.profiles.for_pod(pod) is None:
                continue
            if self.cache.is_assumed(pod):
                continue
            if not self.queue.contains(key):
                self.queue.add(pod)
                requeued += 1
        # device-side state: full mirror re-upload + breaker to closed
        for fwk in self.profiles:
            tpu = fwk.tpu
            mirror = getattr(tpu, "_mirror", None)
            partials = getattr(tpu, "_partials", None)
            if mirror is not None:
                with self.cache.lock:
                    mirror.invalidate()
                    if partials is not None:
                        # the resident partials belong to the same
                        # generation history as the mirror: a new leader
                        # recomputes them whole (warm failover must not
                        # inherit a predecessor's warm rows)
                        partials.invalidate()
            breaker = getattr(tpu, "breaker", None)
            if breaker is not None:
                breaker.reset()
        self.metrics.leader_reconcile_total.inc()
        if requeued:
            log.info(
                "leadership reconcile: re-queued %d uncommitted pod(s)",
                requeued,
            )

    # -- binding stage (the dedicated bind worker) -------------------------

    # a wave that failed this many whole-wave commits splits into per-pod
    # commits (the poison-wave escape hatch): one retry, then isolation
    _MAX_WAVE_ATTEMPTS = 1

    def _bind_worker(self) -> None:
        while True:
            with self._wave_cv:
                while not self._waves and not self._binder_stop:
                    self._wave_cv.wait(0.2)
                if not self._waves:
                    return  # stopping and drained
                entries, attempts = self._waves.popleft()
                self._wave_active = True
                self._wave_cv.notify_all()
            # entries not yet committed or failed: the crash handler
            # requeues exactly this remainder, so a crash-grade fault at
            # ANY point (first commit, retry bookkeeping, mid-split)
            # loses nothing to the assume-TTL
            remaining = list(entries)
            try:
                try:
                    self._commit_wave(entries)
                    remaining = []
                except Exception:  # noqa: BLE001 — wave containment
                    # a whole-wave fault must not kill the binding stage
                    # for the process's lifetime NOR park its pods on
                    # the assume-TTL: retry the wave once, then treat it
                    # as poison and split to per-pod commits with
                    # bounded per-pod failure handling
                    if attempts < self._MAX_WAVE_ATTEMPTS:
                        logging.getLogger(__name__).exception(
                            "bind wave failed (attempt %d); retrying",
                            attempts,
                        )
                        with self._wave_cv:
                            self._waves.appendleft((entries, attempts + 1))
                            self._wave_cv.notify_all()
                        remaining = []
                    else:
                        logging.getLogger(__name__).exception(
                            "bind wave failed twice; splitting poison "
                            "wave into per-pod commits"
                        )
                        self.metrics.binder_poison_waves.inc()
                        while remaining:
                            entry = remaining[0]
                            try:
                                self._commit_wave([entry])
                            except Exception:  # noqa: BLE001 — per-pod
                                logging.getLogger(__name__).exception(
                                    "per-pod commit failed for %s; "
                                    "requeueing", pod_key(entry[1].pod),
                                )
                                self._fail_bind(entry[0], entry[1])
                            remaining.pop(0)
            except BaseException:
                # injected crash / interpreter-level fault: the worker
                # is about to die — put the unprocessed remainder back
                # for the restarted worker (_ensure_binder)
                with self._wave_cv:
                    if remaining:
                        self._waves.appendleft((remaining, attempts + 1))
                    self._wave_active = False
                    self._wave_cv.notify_all()
                raise
            with self._wave_cv:
                self._wave_active = False
                self._wave_cv.notify_all()

    def _ensure_binder(self) -> None:
        """Binder watchdog: restart the binding worker if it died (a
        crash-grade fault escaped containment).  Called from the hot
        loop, the wave dispatch path and flush_binds, so direct
        schedule_batch() callers recover too."""
        # double-checked locking: the hot loop calls this every cycle and
        # the worker is almost always alive — the lock-free probe is the
        # fast path; the locked re-check below is authoritative
        if self._bind_thread.is_alive() or self._binder_stop:  # graftlint: disable=guarded-by
            return
        with self._wave_cv:
            if self._bind_thread.is_alive() or self._binder_stop:
                return
            # the dead worker can't clear its active flag; a stale True
            # would wedge flush_binds forever
            self._wave_active = False
            self.metrics.binder_restarts.inc()
            logging.getLogger(__name__).error(
                "binding worker died; restarting (binder supervision)"
            )
            self._bind_thread = threading.Thread(
                target=self._bind_worker, name="bind-wave", daemon=True
            )
            self._bind_thread.start()
            self._wave_cv.notify_all()

    def _dispatch_wave_async(self, wave: List[tuple]) -> None:
        """Hand a bind wave to the binding stage; blocks only when the
        bounded backlog is full (commit slower than solve — the
        backpressure that keeps requeue latency bounded)."""
        self._ensure_binder()
        with self._wave_cv:
            while len(self._waves) >= self._max_wave_backlog:
                self._wave_cv.wait(0.2)
                if not self._bind_thread.is_alive():
                    break  # watchdog's restart will drain the backlog
            self._waves.append((wave, 0))
            self._wave_cv.notify_all()
        self._ensure_binder()

    def flush_binds(self, timeout: float = 30.0) -> bool:
        """Block until every dispatched bind wave has committed (tests
        and shutdown; the hot path never waits).  True on drained."""
        deadline = time.monotonic() + timeout
        while True:
            self._ensure_binder()
            with self._wave_cv:
                # predicate loop under ONE acquisition (graftlint
                # atomicity cv-discipline); breaks out to re-run the
                # binder watchdog when the worker died mid-drain — a
                # dead worker can never notify this cv again
                while (
                    self._waves or self._wave_active or self._stream_inflight
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    self._wave_cv.wait(min(remaining, 0.2))
                    if not self._bind_thread.is_alive():
                        break
                else:
                    return True

    # -- per-thread in-flight cycle tracking ------------------------------

    def _inflight_set(self, cycle: Optional["_Cycle"]) -> None:
        ident = threading.get_ident()
        with self._inflight_lock:
            if cycle is None:
                self._inflight_cycles.pop(ident, None)
            else:
                self._inflight_cycles[ident] = cycle

    def _inflight_get(self) -> Optional["_Cycle"]:
        with self._inflight_lock:
            return self._inflight_cycles.get(threading.get_ident())

    # -- speculative solve overlap ----------------------------------------

    def _spec_token(self) -> int:
        """The wave-failure generation a speculative dispatch records;
        any commit failure / fence bumps it (see _note_commit_failure)."""
        with self._spec_lock:
            return self._wave_fail_gen

    def _spec_invalidated(self, token: int) -> bool:
        with self._spec_lock:
            return self._wave_fail_gen != token

    def _note_commit_failure(self) -> None:
        """A staged placement was released on the commit side (failed
        sub-wave, fenced wave, PreBind error): any batch dispatched
        speculatively over the released assumes must invalidate."""
        with self._spec_lock:
            self._wave_fail_gen += 1

    def _waves_in_flight(self) -> bool:
        with self._wave_cv:
            return bool(
                self._waves or self._wave_active or self._stream_inflight
            )

    # -- streamed sub-wave commits ----------------------------------------

    def _dispatch_subwave_async(self, entries: List[tuple], sid: int) -> None:
        """Hand one store shard's staged slice of a wave to the commit
        pool immediately (before the rest of the wave stages).  Bounded
        by 2x the pool width so a slow store backpressures the solve
        stage instead of growing an unbounded in-flight set."""
        faults.fire("binder.stream_subwave", pods=len(entries), shard=sid)
        cap = 2 * self._subwave_width
        with self._wave_cv:
            while self._stream_inflight >= cap and not self._binder_stop:
                self._wave_cv.wait(0.2)
            self._stream_inflight += 1
            _ledger.push("stream_inflight", id(self))
            self._wave_cv.notify_all()
        try:
            self._commit_pool.submit(self._commit_stream_subwave, entries)
        except BaseException:
            with self._wave_cv:
                self._stream_inflight -= 1
                _ledger.pop("stream_inflight", id(self))
                self._wave_cv.notify_all()
            raise

    def _commit_stream_subwave(self, entries: List[tuple]) -> None:
        """One streamed per-shard sub-wave on the commit pool.  The
        wave-retry/poison machinery stays with the whole-wave binder
        path; a streamed sub-wave commits once and a whole-sub-wave
        fault requeues its pods with backoff (bound-exactly-once per
        sub-wave holds: the mutator's already-bound guard plus fencing
        reject any duplicate commit)."""
        try:
            self._commit_wave(entries)
        except BaseException:  # noqa: BLE001 — crash-grade containment:
            # the pool thread must survive and the pods must not strand
            # on the assume TTL
            logging.getLogger(__name__).exception(
                "streamed sub-wave commit failed; requeueing %d pod(s)",
                len(entries),
            )
            for fwk, info, _, _ in entries:
                try:
                    self._fail_bind(fwk, info)
                except Exception:  # noqa: BLE001
                    logging.getLogger(__name__).exception(
                        "streamed sub-wave requeue failed for %s",
                        pod_key(info.pod),
                    )
        finally:
            with self._wave_cv:
                self._stream_inflight -= 1
                _ledger.pop("stream_inflight", id(self))
                self._wave_cv.notify_all()

    def _solve_window(self, start: float, end: float) -> None:
        with self._solve_lock:
            self._solve_windows.append((start, end))
            self._solve_open = None

    def _solve_overlap(self, t0: float, t1: float) -> float:
        """Seconds of [t0, t1] that intersected device-solve windows —
        the realized pipeline overlap for one wave commit."""
        with self._solve_lock:
            spans = list(self._solve_windows)
            if self._solve_open is not None:
                spans.append((self._solve_open, t1))
        total = 0.0
        for s, e in spans:
            total += max(0.0, min(e, t1) - max(s, t0))
        return min(total, max(t1 - t0, 0.0))

    def _commit_wave(self, wave: List[tuple]) -> None:
        """Commit one bind wave: PreBind per pod, then ONE store
        transaction for every surviving bind, then the per-pod success
        tail.  Failures split per pod back to individual requeue — a bad
        pod never takes its wave down."""
        faults.fire("binder.commit_wave", pods=len(wave))
        t0 = self._clock()
        binds: List[tuple] = []
        for fwk, info, node_name, t_attempt in wave:
            try:
                fwk.run_pre_bind(info.pod, node_name)
            except Exception:  # noqa: BLE001 — per-pod containment
                self._fail_bind(fwk, info)
                continue
            binds.append((fwk, info, node_name, t_attempt))
        if binds:
            def bind_mutator(node_name: str):
                def mutate(pod: api.Pod) -> None:
                    if pod.spec.node_name and pod.spec.node_name != node_name:
                        # bound-exactly-once guard: a retried wave must
                        # never move an already-bound pod (same-node
                        # recommit is an idempotent no-op-shaped write)
                        raise st.Conflict(
                            f"pod already bound to {pod.spec.node_name}"
                        )
                    pod.spec.node_name = node_name
                    pod.status.phase = "Running"
                return mutate

            # stale-leader write fencing: every sub-wave commits only
            # while our lease acquisition is still current (a deposed
            # leader's late sub-wave is rejected inside its transaction
            # — the Fenced path below requeues; the pods belong to the
            # successor now)
            fence = None
            if self.leader_elector is not None:
                token = getattr(self.leader_elector, "fence_token", None)
                if token is not None:
                    fence = token()
            failed = self._commit_subwaves(binds, bind_mutator, fence)
            done: List[api.Pod] = []
            for fwk, info, node_name, t_attempt in binds:
                if pod_key(info.pod) in failed:
                    self._fail_bind(fwk, info)
                    continue
                done.append(info.pod)
                self._finish_bound(
                    fwk, info, node_name, t_attempt, finish_binding=False
                )
            # TTL countdown for the whole wave under one lock/clock read
            self.cache.finish_binding_all(done)
        dt = self._clock() - t0
        self.metrics.commit_wave_duration.observe(dt)
        self.metrics.commit_wave_size.observe(float(len(wave)))
        if self.window_ctl is not None:
            self.window_ctl.note_commit(len(wave), dt)
        self.metrics.pipeline_overlap.observe(
            self._solve_overlap(t0, self._clock())
        )

    def _commit_subwaves(self, binds, bind_mutator, fence) -> set:
        """Commit one bind wave as per-store-shard SUB-waves — each an
        atomic ``update_wave`` transaction on its shard, committed
        CONCURRENTLY (up to commit_subwave_concurrency) so shard A's
        journal append / watch fan-out overlaps shard B's and the next
        solve.  A 1-shard store (or a wave whose pods all live on one
        shard) keeps the single-transaction path.  Returns the set of
        pod keys that must requeue (per-object errors, a fenced
        sub-wave, or a whole-sub-wave failure)."""
        shard_of = getattr(self.store, "shard_index", None)
        groups: "Dict[int, List[tuple]]" = {}
        for entry in binds:
            sid = (
                shard_of("Pod", entry[1].pod.meta.namespace)
                if shard_of is not None else 0
            )
            groups.setdefault(sid, []).append(entry)

        def commit_group(sid, group):
            updates = [
                (info.pod.meta.name, info.pod.meta.namespace,
                 bind_mutator(node_name))
                for _, info, node_name, _ in group
            ]
            t_g = self._clock()
            try:
                # the binder already partitioned by shard_index: the
                # shard hint lets the store skip re-hashing every pod
                # (the streamed hand-off fast path)
                kwargs = {"fence": fence}
                if shard_of is not None:
                    kwargs["shard_hint"] = sid
                _, errs = self.store.update_wave("Pod", updates, **kwargs)
                bad = set(errs)
            except st.Fenced:
                logging.getLogger(__name__).warning(
                    "bind sub-wave fenced (leadership lost since "
                    "staging); requeueing %d pod(s) for the new leader",
                    len(group),
                )
                bad = {pod_key(info.pod) for _, info, _, _ in group}
            except Exception:  # noqa: BLE001 — sub-wave containment
                logging.getLogger(__name__).exception(
                    "sub-wave transaction failed; requeueing its pods"
                )
                bad = {pod_key(info.pod) for _, info, _, _ in group}
            return bad, self._clock() - t_g

        failed: set = set()
        durations: List[float] = []
        t_all = self._clock()
        if len(groups) > 1 and self._commit_pool is not None:
            futures = [
                self._commit_pool.submit(commit_group, sid, g)
                for sid, g in groups.items()
            ]
            for f in futures:
                bad, dt = f.result()
                failed |= bad
                durations.append(dt)
        else:
            for sid, g in groups.items():
                bad, dt = commit_group(sid, g)
                failed |= bad
                durations.append(dt)
        wall = self._clock() - t_all
        for dt in durations:
            self.metrics.commit_subwave_duration.observe(dt)
        # realized cross-shard commit concurrency: sub-wave work that
        # ran while another sub-wave of this wave was also committing
        self.metrics.commit_subwave_overlap.observe(
            max(sum(durations) - wall, 0.0)
        )
        return failed

    def _fail_bind(self, fwk: Framework, info: QueuedPodInfo) -> None:
        """The binding stage's per-pod failure tail: forget the assume,
        roll back reservations, requeue with backoff.  Also bumps the
        wave-failure generation: a batch dispatched speculatively over
        this (now released) assume invalidates at harvest."""
        self._note_commit_failure()
        released = self.cache.forget(info.pod)
        fwk.run_unreserve(info.pod)
        if released:
            # the assume had accounted real capacity; its release is an
            # AssignedPodDelete-shaped event — without it, pods parked on
            # REASON_RESOURCES would sleep until the flush interval even
            # though the space just came back
            self.queue.move_for_event("AssignedPodDelete")
        self.metrics.schedule_attempts.inc("error")
        self.queue.requeue_backoff(info)

    def _run(self, lane_idx: int = 0) -> None:
        # The solve-side pipeline: the LAST profile group of cycle N stays
        # a device future (DeviceSolve) while the next pop's accumulation
        # window runs — the device solves and the readback transfers while
        # the host collects arrivals, instead of the host idling inside
        # np.asarray.  The deferred group is decoded and staged BEFORE the
        # next batch encodes, so snapshots still see every assume.
        #
        # Each profile LANE runs this loop over its own disjoint pod
        # classes (multi-profile configs); lane 0 is the LEAD lane —
        # leadership reconciliation and the assume-TTL sweep run there
        # only, once per pass, never once per lane.
        lead = lane_idx == 0
        profiles = self._lane_profiles[lane_idx]
        cycle: Optional[_Cycle] = None
        while not self._stop.is_set():
            self._ensure_binder()
            if self.leader_elector and not self.leader_elector.is_leader():
                cycle = self._finish_contained(cycle)
                time.sleep(0.05)
                continue
            if self._reconcile_needed.is_set():
                if not lead:
                    # reconciliation is in flight on the lead lane: a
                    # follower lane must not dispatch over un-reconciled
                    # caches — wait for the lead to clear the flag
                    cycle = self._finish_contained(cycle)
                    time.sleep(0.01)
                    continue
                # first pass after start or (re)acquired leadership:
                # reconcile local state against the store BEFORE popping
                self._reconcile_needed.clear()
                try:
                    self._reconcile_leadership()
                except Exception:  # noqa: BLE001 — containment
                    logging.getLogger(__name__).exception(
                        "leadership reconcile failed; continuing"
                    )
            try:
                # with a solve in flight, the pop is the OVERLAP window —
                # bound it by the accumulation window so staging of the
                # deferred group never waits the full idle timeout
                timeout = 0.2 if cycle is None else min(
                    0.05, self.config.batch_window_seconds or 0.05
                )
                batch = self.queue.pop_batch(
                    self.batch_size, timeout=timeout, profiles=profiles
                )
            except Exception:  # noqa: BLE001
                batch = []
            if (
                batch
                and self.leader_elector
                and not self.leader_elector.is_leader()
            ):
                # leadership was lost INSIDE the pop window: a
                # stepped-down scheduler must not dispatch — hand the
                # batch back and wait for re-acquisition
                for info in batch:
                    self.queue.requeue_backoff(info)
                batch = []
            try:
                if cycle is not None:
                    self._finish_cycle(cycle)
                    cycle = None
                if batch:
                    cycle = self._dispatch_batch(batch)
            except Exception:  # noqa: BLE001 — per-cycle containment
                # the reference contains per-cycle errors (ScheduleOne
                # logs and returns; the wait.Until loop re-enters) — one
                # lost race must not kill the scheduling thread for the
                # process's lifetime.  Salvage first: popped pods the
                # dead cycle never dispositioned go back to the queue
                # instead of stranding in the 'inflight' tier.
                self._salvage_cycle(self._inflight_get())
                cycle = None
                logging.getLogger(__name__).exception(
                    "schedule_batch cycle failed; continuing"
                )
            if lead:
                for pod in self.cache.cleanup_expired():
                    # binding never confirmed: give the pod another chance
                    self.queue.add(pod)
        self._finish_contained(cycle)

    def _salvage_cycle(self, cycle: Optional["_Cycle"]) -> None:
        """A cycle died mid-flight: dispatch whatever bind-wave entries
        it had fully staged (assumed + Permit-allowed — safe to commit),
        then requeue every popped pod no terminal path owned, forgetting
        any assume the dead cycle left behind.  The chaos invariant this
        maintains: every popped pod ends bound or back in the queue,
        never wedged inflight."""
        self._inflight_set(None)
        if cycle is None:
            return
        if cycle.wave:
            staged, cycle.wave = cycle.wave, []
            for _, info, _, _ in staged:
                cycle.handled.add(pod_key(info.pod))
            try:
                self._dispatch_wave_async(staged)
            except Exception:  # noqa: BLE001
                logging.getLogger(__name__).exception(
                    "salvage: staged wave dispatch failed; requeueing"
                )
                for fwk, info, _, _ in staged:
                    self._fail_bind(fwk, info)
        for info in cycle.batch:
            key = pod_key(info.pod)
            if key in cycle.handled:
                continue
            cycle.handled.add(key)
            if self.cache.is_assumed(info.pod):
                # the dead cycle assumed it but lost it before staging
                self.cache.forget(info.pod)
            self.metrics.schedule_attempts.inc("error")
            self.queue.requeue_backoff(info)

    def _finish_contained(self, cycle: Optional["_Cycle"]) -> Optional["_Cycle"]:
        if cycle is not None:
            try:
                self._finish_cycle(cycle)
            except Exception:  # noqa: BLE001
                self._salvage_cycle(self._inflight_get())
                logging.getLogger(__name__).exception(
                    "deferred cycle finalize failed"
                )
        return None

    # -- the batched scheduling cycle -------------------------------------

    def schedule_batch(self, timeout: Optional[float] = None) -> Dict[str, int]:
        """One synchronous solve-stage cycle: drain -> device solve ->
        assume each placement -> hand the bind wave to the binding stage
        -> park failures.  Returns counters for tests/metrics.

        `scheduled` counts pods staged into the bind wave (assumed, past
        Permit): the wave commits asynchronously, and a bind error later
        splits that pod back to requeue (metrics record it as an error).
        Callers that need the binds durable call flush_binds().

        The hot loop (_run) uses the same _dispatch_batch/_finish_cycle
        halves but defers the finalize across the next pop window — this
        entry point finishes the cycle in place so direct callers (tests,
        single-step drivers) keep strict pop->solve->stage semantics."""
        batch = self.queue.pop_batch(self.batch_size, timeout=timeout)
        if not batch:
            return {"popped": 0, "scheduled": 0, "unschedulable": 0,
                    "bind_errors": 0}
        try:
            return self._finish_cycle(self._dispatch_batch(batch))
        except Exception:
            # direct callers see the error, but popped pods must not
            # strand inflight (the same salvage the hot loop runs)
            self._salvage_cycle(self._inflight_get())
            raise

    def _dispatch_batch(self, batch: List[QueuedPodInfo]) -> "_Cycle":
        """The dispatch half of one cycle: group the popped batch by
        profile, encode + dispatch each group's device solve.  Each group
        runs its FULL cycle (solve -> assume -> bind) before the next
        group solves — assume lands the placements in the shared state,
        so a later profile's snapshot sees them; only the LAST group's
        decode+staging is left pending for _finish_cycle (the readback
        the hot loop overlaps with the next pop window)."""
        stats = {"popped": len(batch), "scheduled": 0, "unschedulable": 0,
                 "bind_errors": 0}
        if not self._speculation_enabled:
            # speculative_solve=false: strict solve-vs-commit
            # serialization — a new batch dispatches only over durably
            # committed waves (the rollback knob; the default pipeline
            # overlaps and invalidates on failure instead)
            self.flush_binds(timeout=30.0)
        # Encode under the cache lock (informer threads mutate the same
        # ClusterState/vocabularies); solve outside it.  A pod whose spec
        # can't be encoded (cap overflow, unsupported field) must only
        # reject that pod, not kill the loop (the reference marks the one
        # pod unschedulable, handleSchedulingFailure).
        reservations = self.cache.nominations_excluding(
            {pod_key(info.pod) for info in batch}
        )
        # slow cycles self-describe on EVERY exit path (utiltrace
        # LogIfLong, schedule_one.go:391-431); threshold is generous
        # because first-shape compiles legitimately run tens of seconds.
        # _finish_cycle's log_if_long is the ONE emission point — the old
        # with-block exit double-logged every over-threshold trace.
        trace = Trace("schedule_batch", threshold=1.0, pods=len(batch))
        cycle = _Cycle(stats, trace, reservations, batch)
        self._inflight_set(cycle)
        if self._speculation_enabled and self._waves_in_flight():
            # SPECULATIVE dispatch: this batch's encode/solve runs over
            # placements an in-flight wave only ASSUMED.  Record the
            # wave-failure generation — a commit failure/fence before
            # this cycle harvests invalidates it (requeue, not stage).
            self.metrics.speculative_solves_total.inc()
            faults.fire("solve.speculate", pods=len(batch))
            cycle.spec_token = self._spec_token()
        # A pod can be popped twice into one accumulation window (delete
        # + recreate races a mid-cycle requeue): the duplicate would make
        # cache.assume raise "already assumed" downstream — requeue it
        # per-pod here instead of letting it near the solve.
        seen: set = set()
        deduped: List[QueuedPodInfo] = []
        for info in batch:
            key = pod_key(info.pod)
            if key in seen:
                cycle.handled.add(key)
                self.metrics.schedule_attempts.inc("error")
                self.queue.requeue_backoff(info)
                continue
            seen.add(key)
            deduped.append(info)
        batch = deduped
        by_fwk: Dict[str, List[QueuedPodInfo]] = {}
        for info in batch:
            by_fwk.setdefault(info.pod.spec.scheduler_name, []).append(info)
        groups = [
            (name, group, self.profiles.frameworks.get(name))
            for name, group in by_fwk.items()
        ]
        # another scheduler's pod slipped in.  Normally unreachable (the
        # informer and the reconcile sweep both filter on profile), but a
        # popped pod is an obligation: dropping the group silently would
        # strand its members on the inflight tier forever.  Retire each
        # with an explicit disposition instead.
        for name, group, fwk in groups:
            if fwk is not None:
                continue
            for info in group:
                key = pod_key(info.pod)
                cycle.handled.add(key)
                self.metrics.schedule_attempts.inc("error")
                self.queue.done(info.pod)
                self.events.eventf(
                    info.pod, "Warning", "FailedScheduling",
                    f"no framework profile for scheduler {name!r}",
                )
        groups = [g for g in groups if g[2] is not None]
        for idx, (sched_name, group, fwk) in enumerate(groups):
            solved = self._solve_group_async(cycle, fwk, sched_name, group)
            if solved is None:
                continue
            cycle.solved_any = True
            if idx == len(groups) - 1:
                cycle.pending = solved
            else:
                self._harvest_group(cycle, *solved)
        return cycle

    def _solve_group_async(self, cycle, fwk, sched_name, group):
        """Encode + dispatch one profile group; returns (fwk, name,
        group, DeviceSolve, t_solve) or None when nothing solvable."""
        t_solve = self._clock()
        with self._solve_lock:
            self._solve_open = t_solve
        if cycle.spec_token is not None:
            # speculative encode: bookmark the profile's device-mirror
            # resident buffer (the double-buffer base) so invalidation
            # can drop the speculative delta chain whole
            mirror = getattr(fwk.tpu, "_mirror", None)
            partials = getattr(fwk.tpu, "_partials", None)
            if mirror is not None and sched_name not in cycle.mirror_points:
                with self.cache.lock:
                    cycle.mirror_points[sched_name] = (
                        mirror, mirror.speculation_point()
                    )
                    if partials is not None:
                        # the resident partials double-buffer with the
                        # mirror: one bookmark pair, taken atomically
                        cycle.partials_points[sched_name] = (
                            partials, partials.speculation_point()
                        )
        pods = [info.pod for info in group]
        try:
            ds = fwk.tpu.schedule_pending_async(
                pods, lock=self.cache.lock, reservations=cycle.reservations
            )
        except (OverflowError, ValueError):
            group = self._reject_unencodable(group, fwk, cycle)
            if not group:
                with self._solve_lock:
                    self._solve_open = None
                return None
            try:
                ds = fwk.tpu.schedule_pending_async(
                    [info.pod for info in group], lock=self.cache.lock,
                    reservations=cycle.reservations,
                )
            except (OverflowError, ValueError):
                # cumulative/batch-level encode failure even though
                # each pod encodes alone: park the whole group rather
                # than killing the scheduler thread
                with self._solve_lock:
                    self._solve_open = None
                for info in group:
                    cycle.handled.add(pod_key(info.pod))
                    self.metrics.schedule_attempts.inc("error")
                    self.queue.add_unschedulable(
                        info, reason=assign_ops.REASON_UNENCODABLE
                    )
                return None
        cycle.trace.step(f"encode[{sched_name}]")
        return (fwk, sched_name, group, ds, t_solve)

    def _misspeculate_group(self, cycle, fwk, sched_name, group, ds) -> None:
        """A wave this group's solve speculated over failed or was
        fenced after the dispatch: the solve ran against assumed
        placements that no longer hold.  Discard the solve undecoded
        (releasing its dispatch slot), roll the profile's mirror back to
        its pre-speculation resident buffer, and requeue EXACTLY this
        batch with backoff — bounded, because attempts already counted
        at pop and backoff grows per retry."""
        if hasattr(ds, "release_slot"):
            ds.release_slot()
        point = cycle.mirror_points.get(sched_name)
        if point is not None:
            mirror, bookmark = point
            with self.cache.lock:
                mirror.rollback(bookmark)
                ppoint = cycle.partials_points.get(sched_name)
                if ppoint is not None:
                    # partials roll back WITH the mirror: warm rows must
                    # never outlive the resident tensors they were
                    # evaluated against (partials_rollbacks_total)
                    partials, pbookmark = ppoint
                    partials.rollback(pbookmark)
        self.metrics.misspeculation_total.inc()
        logging.getLogger(__name__).info(
            "mis-speculation: requeueing %d pod(s) of profile %s "
            "(a wave failed/fenced after the speculative dispatch)",
            len(group), sched_name,
        )
        for info in group:
            cycle.handled.add(pod_key(info.pod))
            self.queue.requeue_backoff(info)

    def _harvest_group(self, cycle, fwk, sched_name, group, ds, t_solve):
        """Decode one dispatched group (the coalesced readback) and stage
        its placements."""
        if cycle.spec_token is not None and self._spec_invalidated(
            cycle.spec_token
        ):
            self._misspeculate_group(cycle, fwk, sched_name, group, ds)
            return
        names = fwk.tpu.finalize_pending(
            [info.pod for info in group], ds, lock=self.cache.lock,
            reservations=cycle.reservations,
        )
        # the breaker's retry/fallback may have replaced the solve the
        # names came from — read telemetry off the effective one, never
        # the sick original (its decode raises)
        ds = getattr(fwk.tpu, "last_solve", None) or ds
        lt = fwk.tpu.last_timings or {}
        encode_s = float(lt.get("encode_s", 0.0))
        compile_s = float(lt.get("compile_s", 0.0))
        decode_wait = float(lt.get("decode_wait_s", 0.0))
        overlap_s = float(lt.get("decode_overlap_s", 0.0))
        now = self._clock()
        # overlap window = the DEVICE half only: the encode holds the
        # cache lock, which a concurrent wave commit also needs, so only
        # the device dispatch truly pipelines against commits
        self._solve_window(
            min(t_solve + encode_s + compile_s, now), now
        )
        # one device dispatch solved len(group) pods.  batch_solve
        # observes the EXPOSED solve cost — encode + compile + the decode
        # wait the host actually blocked on; readback hidden behind the
        # pop window shows up in decode_overlap instead.  The
        # reference-named per-pod algorithm metric gets the per-pod share
        # so harness percentiles stay comparable with the reference's
        # per-ScheduleOne numbers.
        dt_exposed = encode_s + compile_s + decode_wait
        if self.window_ctl is not None:
            # compile walls are one-off; the steady per-pod solve cost
            # the window should size against excludes them
            self.window_ctl.note_solve(
                len(group), encode_s + decode_wait
            )
        self.metrics.batch_solve_duration.observe(dt_exposed)
        self.metrics.scheduling_algorithm_duration.observe(
            dt_exposed / max(len(group), 1), count=len(group)
        )
        self.metrics.decode_overlap.observe(overlap_s)
        if compile_s > 0.01:
            # a real trace/compile, not dispatch-enqueue noise
            self.metrics.solve_compile_duration.observe(compile_s)
        if ds.wave_count is not None:
            self.metrics.solve_wave_count.observe(float(ds.wave_count))
            self.metrics.solve_wave_fallbacks.observe(
                float(ds.wave_fallbacks or 0)
            )
        if ds.frag_score is not None:
            # slice-family solve: mirror the carve-out telemetry (same
            # coalesced readback as the names — no extra round-trip)
            self.metrics.fragmentation_score.set(float(ds.frag_score))
            self.metrics.slice_carveouts.inc(by=float(ds.carveouts or 0))
            self.metrics.gang_contiguous_placements.inc(
                by=float(ds.contiguous_gangs or 0)
            )
            self.metrics.slice_carveout_fallbacks.inc(
                by=float(ds.carveout_fallbacks or 0)
            )
        # reasons come from the SAME readback as the names; after a gang
        # admission retry the solve result no longer aligns positionally
        # (unplaced pods there are unadmitted gang members — REASON_GANG
        # by construction) and last_result reflects that
        result = fwk.tpu.last_result
        if result is ds.result and ds.reasons() is not None:
            reasons = ds.reasons()
        elif result is not None and result.reasons is not None:
            reasons = [
                int(r) for r in np.asarray(result.reasons)[: len(group)]
            ]
        else:
            reasons = [-1] * len(group)
        cycle.trace.step(f"decode[{sched_name}]")
        self._stage_group(fwk, group, names, reasons, cycle)
        cycle.trace.step(f"commit[{sched_name}]")

    def _finish_cycle(self, cycle: "_Cycle") -> Dict[str, int]:
        """The staging half: decode any deferred group, hand the bind
        wave to the binding stage, run PostFilter, emit trace/metrics."""
        if cycle.pending is not None:
            # time since dispatch = readback/solve hidden behind host work
            cycle.trace.step("overlap")
            pending, cycle.pending = cycle.pending, None
            self._harvest_group(cycle, *pending)
        stats, trace = cycle.stats, cycle.trace
        if cycle.wave:
            # binding stage takes over: the NEXT cycle's pop+solve runs
            # while this wave commits (assume entries already bridge it)
            self._dispatch_wave_async(cycle.wave)
            trace.step("dispatch")
        if cycle.solved_any:
            # PostFilter: preemption for unschedulable pods, highest
            # priority first (handleSchedulingFailure ->
            # Evaluator.Preempt, schedule_one.go:1017, preemption.go:150).
            # The whole batch shares ONE victim-tensor encode + device
            # dry-run (PreemptionEvaluator.shared_pass); victim deletes
            # emit AssignedPodDelete events that requeue the nominee.
            # Under overload the batch is CAPPED at level 1 (the batched
            # solve amortized the per-pod marginal cost — preemption
            # load spikes exactly when the cluster is overloaded, so
            # deferring it outright was backwards) and deferred only at
            # level 2; pods past the cap count into overload_shed_total
            # and stay parked for a later healthy cycle (or the flush
            # interval).
            cycle.failed.sort(key=lambda i: -i.pod.spec.priority)
            t_postfilter = self._clock()
            budget = self.max_preemptions_per_cycle
            level = self.overload.level()
            if level >= 2:
                budget = 0
            elif level == 1:
                budget = max(1, budget // 4)
            eligible = cycle.failed[: self.max_preemptions_per_cycle]
            batch_infos = eligible[:budget]
            try:
                if batch_infos:
                    # concurrent lanes serialize their PostFilter passes:
                    # the evaluator's shared pass caches per-pass state
                    # (victim tensors, priority floor) one pass at a time
                    with self._postfilter_lock, self.preemption.shared_pass(
                        [info.pod for info in batch_infos]
                    ):
                        for info in batch_infos:
                            fwk = self.profiles.for_pod(info.pod)
                            if fwk is not None and fwk.run_post_filter(
                                info.pod
                            ):
                                stats["preempted"] = (
                                    stats.get("preempted", 0) + 1
                                )
            except (faults.FaultCrash, Exception):  # noqa: BLE001
                # preemption is background work: a crash-grade fault in
                # the batched dry-run must not kill the scheduling
                # thread — the failed pods stay parked and retry on a
                # later cycle (the flush interval is the floor)
                logging.getLogger(__name__).exception(
                    "PostFilter preemption pass failed; continuing"
                )
            if len(eligible) > len(batch_infos):
                self.metrics.overload_shed_total.inc(
                    by=float(len(eligible) - len(batch_infos))
                )
            postfilter_s = self._clock() - t_postfilter
            trace.step("postfilter")
            qs = self.queue.stats()
            for tier, v in qs.items():
                self.metrics.pending_pods.set(v, tier)
        else:
            postfilter_s = 0.0
        trace.log_if_long()
        self.metrics.schedule_batch_duration.observe(trace.total)
        # overload ladder: feed the cycle's PLACEMENT duration — the
        # PostFilter pass is excluded (see OverloadController: shedding
        # must not be driven by the work it sheds) — publish the level,
        # and let the adaptive window react (level 2 pins it wide)
        level = self.overload.note_cycle(
            max(trace.total - postfilter_s, 0.0)
        )
        self.metrics.overload_level.set(float(level))
        if self.window_ctl is not None:
            self.window_ctl.set_overload(level)
            self.metrics.batch_window_ms.set(
                self.window_ctl.window() * 1000.0
            )
        # degraded-mode observability: mirror the breaker and journal
        # recovery state into the registry every cycle (cheap gauge sets)
        breaker = getattr(self.tpu, "breaker", None)
        if breaker is not None:
            self.metrics.solve_breaker_state.set(breaker.state_code())
            self.metrics.solve_fallback_total.set(
                float(breaker.fallback_count())
            )
        # solver executable traces, when the recompile-discipline
        # runtime tracker is armed (bench / GRAFTLINT_SHAPES=1 runs)
        self.metrics.solve_retrace_total.set(float(_retrace.total()))
        # graftcoh resident-epoch audits, when the coherence auditor is
        # armed (bench / GRAFTLINT_COHERENCE=1 runs; 0 disarmed)
        self.metrics.coherence_audits.set(float(_epochs.audits_total()))
        self.metrics.coherence_violations.set(
            float(_epochs.violations_total())
        )
        # graftobl exactly-once ledger, when armed (bench /
        # GRAFTLINT_OBLIGATIONS=1 runs; all 0 disarmed)
        self.metrics.obligations_tracked.set(
            float(_ledger.tracked_total())
        )
        self.metrics.obligation_leaks.set(float(_ledger.leaks_total()))
        self.metrics.obligation_double_discharge.set(
            float(_ledger.double_discharge_total())
        )
        # sharded-solve surface: mesh size in use, device-mirror
        # host→device transfer accounting, and single-chip fallbacks
        self.metrics.solve_shard_count.set(
            float(getattr(self.tpu, "shard_count", 0))
        )
        self.metrics.sharded_solve_fallbacks.set(
            float(getattr(self.tpu, "sharded_fallbacks", 0))
        )
        mirror = getattr(self.tpu, "_mirror", None)
        if mirror is not None:
            self.metrics.mirror_resync_total.set(float(mirror.resync_total))
            self.metrics.mirror_delta_rows.set(
                float(mirror.delta_rows_total)
            )
            # elastic node axis: in-place resident resizes vs re-uploads
            self.metrics.mirror_grow_total.set(float(mirror.grow_syncs))
            self.metrics.mirror_grow_rows.set(
                float(mirror.grow_rows_total)
            )
        est = getattr(self.tpu, "state", None)
        if est is not None:
            self.metrics.node_axis_bucket.set(float(est.node_axis_bucket))
            self.metrics.compactions_total.set(float(est.compactions_total))
            self.metrics.compaction_moved_rows.set(
                float(est.compaction_moved_rows_total)
            )
        # incremental-solve surface: resident-partials hit/recompute
        # accounting across every profile's cache (summed — profiles
        # sync independently, the surface is one control plane)
        p_stats = [
            fwk.tpu._partials.stats()
            for fwk in self.profiles
            if getattr(fwk.tpu, "_partials", None) is not None
        ]
        if p_stats:
            self.metrics.partials_hit_rows.set(
                float(sum(s["hit_rows_total"] for s in p_stats))
            )
            self.metrics.partials_recomputed_rows.set(
                float(sum(s["recomputed_rows_total"] for s in p_stats))
            )
            self.metrics.partials_full_recomputes.set(
                float(sum(s["full_recomputes"] for s in p_stats))
            )
            self.metrics.partials_rollbacks.set(
                float(sum(s["rollbacks"] for s in p_stats))
            )
        # columnar host plane: encode throughput of the most recent
        # snapshot build (summed across profiles would double-count the
        # shared builder — the max is the live figure), framed journal
        # bytes and mean fan-out chunk size mirrored from the store
        enc = max(
            (
                getattr(fwk.tpu, "last_encode_rows_per_s", 0.0)
                for fwk in self.profiles
            ),
            default=0.0,
        )
        if enc:
            self.metrics.encode_rows_per_s.set(float(enc))
        frame_bytes = getattr(self.store, "journal_frame_bytes", None)
        if frame_bytes is not None:
            self.metrics.journal_frame_bytes.set(float(frame_bytes))
        chunks = getattr(self.store, "fanout_chunks", 0)
        if chunks:
            self.metrics.fanout_chunk_size.set(
                float(self.store.fanout_chunk_events) / float(chunks)
            )
        recovered = getattr(self.store, "journal_recovered_records", None)
        if recovered is not None:
            self.metrics.journal_recovered_records.set(float(recovered))
        # crash-restart recovery surface: the store's last recovery cost
        # split, checkpoint count, and fenced late-leader waves
        for attr, gauge in (
            ("recovery_duration_ms", self.metrics.store_recovery_duration_ms),
            ("snapshot_records", self.metrics.store_snapshot_records),
            (
                "journal_suffix_records",
                self.metrics.store_journal_suffix_records,
            ),
            ("checkpoints_total", self.metrics.store_checkpoints_total),
            ("shard_count", self.metrics.store_shard_count),
            ("fenced_writes_total", self.metrics.fenced_writes_total),
        ):
            v = getattr(self.store, attr, None)
            if v is not None:
                gauge.set(float(v))
        # watch fan-out health: mirror the store's backpressure counters
        # (depth / coalesced / expired) and any legacy terminations
        watch_stats = getattr(self.store, "watch_stats", None)
        if watch_stats is not None:
            ws = watch_stats()
            self.metrics.watch_queue_depth.set(
                float(ws["watch_queue_depth"])
            )
            self.metrics.watch_coalesced_total.set(
                float(ws["watch_coalesced_total"])
            )
            self.metrics.watch_expired_total.set(
                float(ws["watch_expired_total"])
            )
            for kind, n in dict(
                getattr(self.store, "terminated_by_kind", {})
            ).items():
                self.metrics.watch_terminated_total.set(float(n), kind)
        # serving plane: feed the adaptive APF ladder (overload level +
        # store depths) and mirror the fleet-wide serving gauges.  The
        # store carries a weakref to the replica set (set by
        # APIServerReplicaSet); exception-contained — serving-plane
        # trouble must never take the scheduling loop down with it.
        plane_ref = getattr(self.store, "serving_plane", None)
        plane = plane_ref() if plane_ref is not None else None
        if plane is not None:
            try:
                plane.note_scheduler(level, self.store)
                sp = plane.serving_stats()
                self.metrics.apf_seats_current.set(
                    float(sp["apf_seats_current"])
                )
                self.metrics.apf_rejected_total.set(
                    float(sp["apf_rejected_total"])
                )
                self.metrics.server_watch_write_stalls_total.set(
                    float(sp["server_watch_write_stalls_total"])
                )
                self.metrics.replica_failovers_total.set(
                    float(sp["replica_failovers_total"])
                )
            except Exception:  # noqa: BLE001 — mirror-only containment
                logging.getLogger(__name__).exception(
                    "serving-plane mirror failed"
                )
        self._inflight_set(None)
        return stats

    def _stage_group(
        self,
        fwk: Framework,
        group: List[QueuedPodInfo],
        names: List[Optional[str]],
        reasons: List[int],
        cycle: "_Cycle",
    ) -> None:
        """Assume one profile's placements and stage them into the bind
        wave (the per-pod tail of ScheduleOne, schedule_one.go:118-133
        batched; the bind itself runs on the binding stage).  Permit
        ordering is preserved: reject aborts here, wait parks the pod on
        its own WaitOnPermit thread exactly as before — only the
        allow-path bind moves into the wave.  Every branch marks the pod
        handled so a mid-cycle fault salvages only truly-orphaned pods.

        A duplicate assume ("already assumed" ValueError — the same pod
        reaching the solve twice despite the dispatch dedup) is contained
        to a per-pod requeue-with-backoff; it never kills the cycle.

        STREAMED sub-wave commits (stream_subwaves, multi-shard stores):
        instead of accumulating the whole group into ``cycle.wave`` and
        dispatching after the full readback+staging, the group is staged
        per STORE SHARD and each shard's slice is handed to the commit
        pool the moment it finishes staging — shard A's journal fsync /
        watch fan-out run while shard B's pods are still staging (and
        while the next solve runs).  Each pod lands in exactly ONE
        streamed sub-wave, and every sub-wave carries the same fence /
        bound-exactly-once semantics as a whole wave."""
        shard_of = getattr(self.store, "shard_index", None)
        if not (self._stream_enabled and shard_of is not None):
            for i, (info, node_name) in enumerate(zip(group, names)):
                entry = self._stage_one(
                    fwk, info, node_name, reasons[i], cycle
                )
                if entry is not None:
                    cycle.wave.append(entry)
            return
        # streamed: bucket the group's indices by owning store shard,
        # stage shard-by-shard, hand each staged slice off immediately
        buckets: Dict[int, List[int]] = {}
        for i, node_name in enumerate(names):
            sid = (
                shard_of("Pod", group[i].pod.meta.namespace)
                if node_name is not None else -1
            )
            buckets.setdefault(sid, []).append(i)
        handoffs: List[float] = []
        for sid, idxs in buckets.items():
            entries: List[tuple] = []
            for i in idxs:
                entry = self._stage_one(
                    fwk, group[i], names[i], reasons[i], cycle
                )
                if entry is not None:
                    entries.append(entry)
            if sid < 0 or not entries:
                continue
            try:
                self._dispatch_subwave_async(entries, sid)
                handoffs.append(self._clock())
            except Exception:  # noqa: BLE001 — hand-off containment:
                # staged (assumed) pods must not strand on the TTL
                logging.getLogger(__name__).exception(
                    "streamed sub-wave hand-off failed; requeueing"
                )
                for e in entries:
                    self._fail_bind(e[0], e[1])
        if handoffs:
            t_end = self._clock()
            for t in handoffs:
                # the commit lead streaming bought this sub-wave over
                # the whole-group hand-off point
                self.metrics.subwave_stream_lead_ms.observe(
                    (t_end - t) * 1000.0
                )

    def _stage_one(self, fwk, info, node_name, reason, cycle):
        """Stage ONE placement (the per-pod tail shared by the whole-wave
        and streamed paths): filter_result veto → assume → Permit.
        Returns a bind-wave entry for the allow path, None when a
        terminal path (park, requeue, WaitOnPermit thread) took the
        pod."""
        stats, failed = cycle.stats, cycle.failed
        t_attempt = self._clock()
        if node_name is not None:
            node_name = fwk.run_filter_result(info.pod, node_name)
            if node_name is None:
                # a later plugin rejected a placement an earlier one
                # may have reserved for (e.g. volume Reserve) — roll
                # the reservations back before parking
                fwk.run_unreserve(info.pod)
        if node_name is None:
            stats["unschedulable"] += 1
            self.metrics.schedule_attempts.inc("unschedulable")
            self.queue.add_unschedulable(info, reason=reason)
            self.events.eventf(
                info.pod, "Warning", "FailedScheduling",
                f"0 nodes available ({_REASON_TEXT.get(reason, 'unschedulable')})",
            )
            failed.append(info)
            cycle.handled.add(pod_key(info.pod))
            return None
        try:
            self.cache.assume(info.pod, node_name)
        except (KeyError, ValueError):
            fwk.run_unreserve(info.pod)
            stats["bind_errors"] += 1
            self.metrics.schedule_attempts.inc("error")
            self.queue.requeue_backoff(info)
            cycle.handled.add(pod_key(info.pod))
            return None
        # Permit (schedule_one.go:231): reject aborts; wait parks
        # the pod in the waiting map and the binding runs on its own
        # thread blocking in WaitOnPermit (:278) — the scheduling
        # loop moves on, like the reference's async bindingCycle
        verdict, timeout = fwk.run_permit(info.pod, node_name)
        if verdict == "reject":
            self.cache.forget(info.pod)
            fwk.run_unreserve(info.pod)
            stats["unschedulable"] += 1
            self.metrics.schedule_attempts.inc("unschedulable")
            self.events.eventf(
                info.pod, "Warning", "FailedScheduling",
                f"permit rejected on node {node_name}",
            )
            self.queue.requeue_backoff(info)
            cycle.handled.add(pod_key(info.pod))
            return None
        if verdict == "wait":
            wp = WaitingPod(info.pod, node_name, timeout)
            self.waiting.add(wp)
            t = threading.Thread(
                target=self._binding_cycle_async,
                args=(fwk, info, node_name, wp, t_attempt),
                name=f"bind-{info.pod.meta.name}",
                daemon=True,
            )
            t.start()
            stats["waiting"] = stats.get("waiting", 0) + 1
            cycle.handled.add(pod_key(info.pod))
            return None
        # staged: assumed + Permit-allowed; the binding stage owns
        # the rest (PreBind -> wave commit -> PostBind)
        stats["scheduled"] += 1
        cycle.handled.add(pod_key(info.pod))
        return (fwk, info, node_name, t_attempt)

    def _bind_tail(self, fwk, info, node_name, t_attempt) -> bool:
        """PreBind -> bind -> PostBind with failure containment: the
        per-pod tail used by WaitOnPermit binding threads, whose pods
        complete outside any wave (the global metrics Registry still
        records them)."""
        try:
            fwk.run_pre_bind(info.pod, node_name)
            self._bind(info.pod, node_name)
        except Exception:
            self._fail_bind(fwk, info)
            return False
        self._finish_bound(fwk, info, node_name, t_attempt)
        return True

    def _finish_bound(
        self, fwk, info, node_name, t_attempt, finish_binding: bool = True
    ) -> None:
        """The success tail of a committed bind: PostBind, Scheduled
        event, TTL countdown, queue drop, metrics."""
        fwk.run_post_bind(info.pod, node_name)
        self.events.eventf(
            info.pod, "Normal", "Scheduled",
            f"Successfully assigned {pod_key(info.pod)} to {node_name}",
        )
        if finish_binding:
            self.cache.finish_binding(info.pod)
        self.queue.done(info.pod)
        self.metrics.schedule_attempts.inc("scheduled")
        self.metrics.scheduling_attempt_duration.observe(
            self._clock() - t_attempt
        )
        self.metrics.pod_scheduling_sli_duration.observe(
            self._clock() - info.initial_attempt_timestamp
        )

    def _binding_cycle_async(
        self, fwk, info, node_name, wp, t_attempt
    ) -> None:
        """WaitOnPermit then the bind tail, on a binding thread
        (schedule_one.go:118's goroutine).  Rejection/timeout forgets the
        assume, rolls back reservations, and requeues with backoff."""
        try:
            verdict = wp.wait()
        finally:
            self.waiting.remove(info.pod)
        if verdict != "allow":
            self.cache.forget(info.pod)
            fwk.run_unreserve(info.pod)
            self.metrics.schedule_attempts.inc("unschedulable")
            self.events.eventf(
                info.pod, "Warning", "FailedScheduling",
                f"permit {verdict} on node {node_name}",
            )
            self.queue.requeue_backoff(info)
            return
        self._bind_tail(fwk, info, node_name, t_attempt)

    def _volume_reserve_plugin(
        self, pod: api.Pod, node_name: str
    ) -> Optional[str]:
        """Reserve (volume_binding.go:369): pick concrete volumes for the
        pod's unbound claims on the chosen node; rejecting the placement
        parks the pod for retry (the solve's selector already restricted
        candidates to topology-feasible nodes, so rejection here means a
        race on volume capacity)."""
        if not any(v.persistent_volume_claim for v in pod.spec.volumes):
            return node_name
        try:
            node = self.store.get("Node", node_name, namespace="")
        except KeyError:
            return None
        return node_name if self.volumes.reserve(pod, node) else None

    def _device_reserve_plugin(
        self, pod: api.Pod, node_name: str
    ) -> Optional[str]:
        """DRA Reserve: assume claim allocations on the chosen node."""
        if not pod.spec.resource_claims:
            return node_name
        try:
            node = self.store.get("Node", node_name, namespace="")
        except KeyError:
            return None
        return node_name if self.devices.reserve(pod, node) else None

    def _preempt_plugin(self, pod: api.Pod) -> Optional[str]:
        """The DefaultPreemption PostFilter plugin (registered on every
        profile; replaceable/augmentable via Framework.register)."""
        if not self.preemption.eligible(pod):
            return None
        result = self.preemption.preempt(pod)
        return result.nominated_node if result else None

    def _reject_unencodable(
        self,
        batch: List[QueuedPodInfo],
        fwk: Optional[Framework] = None,
        cycle: Optional["_Cycle"] = None,
    ) -> List[QueuedPodInfo]:
        """Batch encode failed: find the offending pods by encoding each
        alone against the SAME profile's builder (rare path; the per-pod
        encode is the authoritative validation) and park them
        unschedulable.  Returns the encodable remainder."""
        tpu = fwk.tpu if fwk is not None else self.tpu
        good: List[QueuedPodInfo] = []
        for info in batch:
            try:
                tpu.encode_pending([info.pod], lock=self.cache.lock)
                good.append(info)
            except (OverflowError, ValueError):
                if cycle is not None:
                    cycle.handled.add(pod_key(info.pod))
                self.metrics.schedule_attempts.inc("error")
                # only a pod UPDATE (spec change) can help — no cluster
                # event wakes this reason (queue.move_for_event)
                self.queue.add_unschedulable(
                    info, reason=assign_ops.REASON_UNENCODABLE
                )
        return good

    def _bind(self, pod: api.Pod, node_name: str) -> None:
        """The DefaultBinder POST pods/{name}/binding analogue: write
        nodeName through the API with optimistic concurrency."""
        current = self.store.get("Pod", pod.meta.name, pod.meta.namespace)
        current.spec.node_name = node_name
        current.status.phase = "Running"
        self.store.update(current, copy_result=False)

    # -- warmup ------------------------------------------------------------

    def warmup(self, pods: List[api.Pod], max_batch: Optional[int] = None) -> float:
        """Pre-compile the solver executables a coming workload will hit.

        The reference needs nothing like this (Go compiles ahead of
        time); here first-shape XLA compiles are 10-40 s each, and a
        measured scheduling window that includes them loses the wall
        clock at small scale.  Warmup runs the REAL scheduling path —
        encode + solve, placements discarded, nothing assumed or bound —
        over every power-of-two pod bucket up to the first full batch,
        using caller-supplied template pods so the compiled feature set
        (spread/interpod/ports/...) and constraint-table shapes match
        the workload's.  Combined with the persistent compilation cache
        (utils/compilecache.py) later processes warm in milliseconds.

        Two rounds per bucket: round A against the current (typically
        bound-pod-free) cluster, round B with one template pod assumed —
        the bound_* FeatureFlags flip once the first batch binds, which
        is a NEW executable; without round B the second measured batch
        of a constraint workload would compile mid-window.  For
        constraint-free workloads round B is a jit-cache hit and costs
        an encode (~ms).

        Returns seconds spent.  Never raises: a bucket that fails to
        encode (cap overflow) is skipped — the real cycle handles those
        pods through its own rejection path."""
        t0 = self._clock()
        if not pods or not self.tpu.state._rows:
            return 0.0
        fwk = self.profiles.for_pod(pods[0]) or self.profiles.default
        cap = min(len(pods), max_batch or self.batch_size)
        from ..utils import vocab as vb

        buckets, b = [], self.tpu.builder.limits.min_pods
        top = vb.pad_dim(cap, self.tpu.builder.limits.min_pods)
        while b <= top:
            buckets.append(b)
            b *= 2
        log = logging.getLogger(__name__)

        def warm_bucket(bucket: int) -> None:
            try:
                fwk.tpu.schedule_pending(
                    pods[:bucket], num_pods_hint=bucket, lock=self.cache.lock,
                )
            except Exception:
                log.exception("warmup bucket %d skipped", bucket)

        def warm_all() -> None:
            # buckets in parallel: encode serializes under the cache
            # lock, but XLA compiles release the GIL and overlap —
            # cold warmup is compile-dominated
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=4) as ex:
                list(ex.map(warm_bucket, reversed(buckets)))

        # constraint-free pods can never flip the bound_* feature flags
        # (their count tables have no rows), so one round suffices
        needs_bound_round = any(
            p.spec.topology_spread_constraints
            or (p.spec.affinity and (p.spec.affinity.pod_affinity
                                     or p.spec.affinity.pod_anti_affinity))
            for p in pods
        )
        warm_all()
        if needs_bound_round:
            # round B: one template pod assumed on a live node flips
            # bound_spread/bound_terms/bound_pref — a NEW executable the
            # second measured batch would otherwise compile mid-window
            import copy

            clone = copy.deepcopy(pods[0])
            clone.meta.name = "warmup-bound-pod"
            clone.meta.namespace = pods[0].meta.namespace or "default"
            node0 = next(iter(self.tpu.state._rows))
            try:
                self.cache.assume(clone, node0)  # graftlint: disable=obligations -- the warm_all finally forgets the clone; if THAT forget fails it is logged and cleanup_expired retires the synthetic assume by TTL
            except Exception:
                return self._clock() - t0  # no usable node; round A ran
            try:
                warm_all()
            finally:
                try:
                    self.cache.forget(clone)
                except Exception:
                    log.exception("warmup: forgetting the bound clone failed")
        return self._clock() - t0

    # -- test/bench convenience -------------------------------------------

    def wait_for_idle(self, timeout: float = 30.0) -> bool:
        """True once no pending pods remain in active/backoff/inflight
        (unschedulable pods may remain parked)."""
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            s = self.queue.stats()
            if s["active"] == 0 and s["inflight"] == 0 and s["backoff"] == 0:
                return True
            time.sleep(0.02)
        return False
