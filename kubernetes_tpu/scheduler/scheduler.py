"""The host scheduler: informer-fed cache + queue draining into batched
device solves, with assume/bind/fail-requeue.

Reference mapping (pkg/scheduler/scheduler.go, schedule_one.go):

  Scheduler.run            scheduler.go:438 Run (queue flush + hot loop)
  schedule_batch           the batched schedule_one.go:66 ScheduleOne:
                           NextPod -> schedulePod -> assume -> bind; one
                           device dispatch schedules the whole batch
  _bind                    bindingCycle's DefaultBinder POST
                           (schedule_one.go:962, defaultbinder)
  failure handling         handleSchedulingFailure :1017 ->
                           AddUnschedulableIfNotPresent; bind errors
                           forget the assume and requeue with backoff
  event wiring             eventhandlers.go:287 addAllEventHandlers:
                           informers feed cache (assigned pods, nodes)
                           and queue (pending pods, requeue-on-event)

The scheduling algorithm itself — filters, scores, selectHost, the
assume bookkeeping between pods of one batch — runs on the TPU inside
TPUBatchScheduler (models/batch_scheduler.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..api import store as st
from ..api import types as api
from ..client.informers import InformerFactory
from ..models.batch_scheduler import TPUBatchScheduler
from .cache import SchedulerCache
from .metrics import Registry
from .preemption import PreemptionEvaluator
from .queue import QueuedPodInfo, SchedulingQueue, pod_key


class Scheduler:
    def __init__(
        self,
        store: st.Store,
        batch_size: int = 4096,
        tpu: Optional[TPUBatchScheduler] = None,
        assume_ttl: float = 30.0,
        clock=time.monotonic,
    ):
        self.store = store
        self.batch_size = batch_size
        self.tpu = tpu or TPUBatchScheduler()
        self.cache = SchedulerCache(self.tpu.state, ttl=assume_ttl, clock=clock)
        self.queue = SchedulingQueue(clock=clock)
        self.metrics = Registry()
        self.preemption = PreemptionEvaluator(
            self.tpu, self.cache, store, self.metrics
        )
        # PostFilter budget per cycle: preemption is the exceptional path;
        # cap the per-batch dry-run work so a mass of unschedulable pods
        # can't stall the hot loop.
        self.max_preemptions_per_cycle = 16
        self.informers = InformerFactory(store)
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._wire_handlers()

    # -- event wiring (eventhandlers.go:287) ------------------------------

    def _wire_handlers(self) -> None:
        self.informers.informer("Node").add_handler(self._on_node)
        self.informers.informer("Pod").add_handler(self._on_pod)

    def _on_node(self, typ: str, node: api.Node, old) -> None:
        if typ == st.ADDED:
            self.cache.add_node(node)
            self.queue.move_all_to_active_or_backoff("NodeAdd")
        elif typ == st.MODIFIED:
            self.cache.update_node(node)
            self.queue.move_all_to_active_or_backoff("NodeUpdate")
        elif typ == st.DELETED:
            self.cache.remove_node(node.meta.name)

    def _on_pod(self, typ: str, pod: api.Pod, old) -> None:
        assigned = bool(pod.spec.node_name)
        if typ == st.DELETED:
            if assigned:
                self.cache.remove_pod(pod)
                # a terminated pod frees resources: unschedulable pods
                # may fit now (AssignedPodDelete cluster event)
                self.queue.move_all_to_active_or_backoff("AssignedPodDelete")
            else:
                self.queue.delete(pod)
                self.cache.remove_nomination(pod)
            return
        if assigned:
            # bound (or our own bind echoing back): confirm in cache
            if old is not None and not old.spec.node_name:
                self.queue.done(pod)
            if (
                typ == st.MODIFIED
                and old is not None
                and old.spec.node_name == pod.spec.node_name
            ):
                # already-bound pod changed (in-place resize, label edit):
                # re-account so requested rows track the new spec
                self.cache.update_pod(old, pod)
            else:
                self.cache.add_pod(pod)
            return
        if typ == st.ADDED:
            self.queue.add(pod)
        else:
            self.queue.update(pod)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Start informers + the scheduling loop thread."""
        self.informers.informer("Node").start()
        self.informers.informer("Pod").start()
        self.informers.wait_for_sync()
        self._thread = threading.Thread(
            target=self._run, name="scheduler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
        if self._thread:
            # a device solve mid-compile can run tens of seconds; tearing
            # the interpreter down under an XLA compile aborts the process,
            # so wait the compile out
            self._thread.join(timeout=120)
        self.informers.stop()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.schedule_batch(timeout=0.2)
            for pod in self.cache.cleanup_expired():
                # binding never confirmed: give the pod another chance
                self.queue.add(pod)

    # -- the batched scheduling cycle -------------------------------------

    def schedule_batch(self, timeout: Optional[float] = None) -> Dict[str, int]:
        """One batched cycle: drain -> device solve -> assume+bind each
        placement -> park failures.  Returns counters for tests/metrics."""
        batch = self.queue.pop_batch(self.batch_size, timeout=timeout)
        stats = {"popped": len(batch), "scheduled": 0, "unschedulable": 0,
                 "bind_errors": 0}
        if not batch:
            return stats
        t0 = self._clock()
        # Encode under the cache lock (informer threads mutate the same
        # ClusterState/vocabularies); solve outside it.  A pod whose spec
        # can't be encoded (cap overflow, unsupported field) must only
        # reject that pod, not kill the loop (the reference marks the one
        # pod unschedulable, handleSchedulingFailure).
        reservations = self.cache.nominations_excluding(
            {pod_key(info.pod) for info in batch}
        )
        try:
            names = self.tpu.schedule_pending(
                [info.pod for info in batch], lock=self.cache.lock,
                reservations=reservations,
            )
        except (OverflowError, ValueError):
            batch = self._reject_unencodable(batch)
            if not batch:
                return stats
            names = self.tpu.schedule_pending(
                [info.pod for info in batch], lock=self.cache.lock,
                reservations=reservations,
            )
        self.metrics.scheduling_algorithm_duration.observe(self._clock() - t0)

        failed: List[QueuedPodInfo] = []
        for info, node_name in zip(batch, names):
            t_attempt = self._clock()
            if node_name is None:
                stats["unschedulable"] += 1
                self.metrics.schedule_attempts.inc("unschedulable")
                self.queue.add_unschedulable(info)
                failed.append(info)
                continue
            try:
                self.cache.assume(info.pod, node_name)
            except (KeyError, ValueError):
                stats["bind_errors"] += 1
                self.metrics.schedule_attempts.inc("error")
                self.queue.requeue_backoff(info)
                continue
            try:
                self._bind(info.pod, node_name)
            except Exception:
                self.cache.forget(info.pod)
                stats["bind_errors"] += 1
                self.metrics.schedule_attempts.inc("error")
                self.queue.requeue_backoff(info)
                continue
            self.cache.finish_binding(info.pod)
            self.queue.done(info.pod)
            stats["scheduled"] += 1
            self.metrics.schedule_attempts.inc("scheduled")
            self.metrics.scheduling_attempt_duration.observe(
                self._clock() - t_attempt
            )
            self.metrics.pod_scheduling_sli_duration.observe(
                self._clock() - info.initial_attempt_timestamp
            )

        # PostFilter: preemption for unschedulable pods, highest priority
        # first (handleSchedulingFailure -> Evaluator.Preempt,
        # schedule_one.go:1017, preemption.go:150).  Victim deletes emit
        # AssignedPodDelete events that requeue the nominee.
        failed.sort(key=lambda i: -i.pod.spec.priority)
        for info in failed[: self.max_preemptions_per_cycle]:
            if self.preemption.eligible(info.pod):
                result = self.preemption.preempt(info.pod)
                if result is not None:
                    stats["preempted"] = stats.get("preempted", 0) + 1

        qs = self.queue.stats()
        for tier, v in qs.items():
            self.metrics.pending_pods.set(v, tier)
        return stats

    def _reject_unencodable(self, batch: List[QueuedPodInfo]) -> List[QueuedPodInfo]:
        """Batch encode failed: find the offending pods by encoding each
        alone (rare path; the per-pod encode is the authoritative
        validation, so checks are never duplicated here) and park them
        unschedulable.  Returns the encodable remainder."""
        good: List[QueuedPodInfo] = []
        for info in batch:
            try:
                self.tpu.encode_pending([info.pod], lock=self.cache.lock)
                good.append(info)
            except (OverflowError, ValueError):
                self.metrics.schedule_attempts.inc("error")
                self.queue.add_unschedulable(info)
        return good

    def _bind(self, pod: api.Pod, node_name: str) -> None:
        """The DefaultBinder POST pods/{name}/binding analogue: write
        nodeName through the API with optimistic concurrency."""
        current = self.store.get("Pod", pod.meta.name, pod.meta.namespace)
        current.spec.node_name = node_name
        current.status.phase = "Running"
        self.store.update(current)

    # -- test/bench convenience -------------------------------------------

    def wait_for_idle(self, timeout: float = 30.0) -> bool:
        """True once no pending pods remain in active/backoff/inflight
        (unschedulable pods may remain parked)."""
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            s = self.queue.stats()
            if s["active"] == 0 and s["inflight"] == 0 and s["backoff"] == 0:
                return True
            time.sleep(0.02)
        return False
