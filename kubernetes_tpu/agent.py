"""Node agent v1: the kubelet's pod-lifecycle half as a per-pod FSM.

Reference: pkg/kubelet — syncLoop (kubelet.go:2338) feeding per-pod
workers (pod_workers.go), probe workers (prober/worker.go) gating the
Ready condition, restart policy enforcement in syncPod, graceful
deletion (kubelet.go HandlePodRemoves + the apiserver's two-phase
delete), and the checkpoint manager (checkpointmanager/
checkpoint_manager.go:36) that lets an agent restart without losing
container state.

The runtime is hollow (kubemark's fake runtime): containers don't run,
but the CONTROL surface is real — probe outcomes, restarts, exits, and
termination are scripted through pod annotations so tests and kubemark
churn can drive every path:

  agent.kubernetes.io/fail-readiness: "true"   readiness probe fails
  agent.kubernetes.io/fail-liveness:  "true"   liveness probe fails
                                               (restart per policy)
  agent.kubernetes.io/exit-after: "1.5"        container exits after
                                               1.5s of running
  agent.kubernetes.io/exit-code:  "1"          ... with this exit code

Annotations are re-read each tick, so a test can flip readiness at
runtime exactly like a real probe starting to fail.

State machine per pod (pod_workers.go's SyncPod/TerminatingPod):

  observed bound ─→ starting ──(startup window)──→ running
        ▲               │                             │ liveness fail /
        │               │◀────── restart ─────────────┘ scripted exit
        │               │ (policy allows; restartCount++)
        │               └──(policy forbids)→ terminal (Succeeded/Failed)
  deletionTimestamp at any point → terminating ──(grace)──→ finalizer
  dropped → object removed (two-phase delete, api/store.py delete()).

Checkpoint: restart counts, start times, and the pod-IP counter are
journaled to a JSON file on every change (atomic replace); a restarted
agent resumes its pods with state intact (kill-and-resume).
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

from .api import store as st
from .api import types as api

FINALIZER = "agent.kubernetes.io/running"

ANN_FAIL_READINESS = "agent.kubernetes.io/fail-readiness"
ANN_FAIL_LIVENESS = "agent.kubernetes.io/fail-liveness"
ANN_EXIT_AFTER = "agent.kubernetes.io/exit-after"
ANN_EXIT_CODE = "agent.kubernetes.io/exit-code"


class _PodWorker:
    """One pod's FSM state (pod_workers.go podSyncStatus)."""

    def __init__(self, pod: api.Pod, now: float):
        self.pod = pod
        self.state = "starting"          # starting | running | terminating | terminal
        self.started_at = now            # current container start (wall)
        self.terminating_since: Optional[float] = None
        self.restart_counts: Dict[str, int] = {}
        self.ready = False
        self.live_fails = 0              # consecutive liveness failures
        self.ready_successes = 0         # consecutive readiness successes
        self.phase = ""                  # terminal phase once decided

    def to_dict(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "started_at": self.started_at,
            "restart_counts": self.restart_counts,
            "ready": self.ready,
            "phase": self.phase,
        }

    def load(self, d: Dict[str, Any]) -> None:
        self.state = d.get("state", "starting")
        self.started_at = d.get("started_at", self.started_at)
        self.restart_counts = dict(d.get("restart_counts", {}))
        self.ready = bool(d.get("ready", False))
        self.phase = d.get("phase", "")


class NodeAgent:
    """One node's kubelet: watches its pods, runs their FSMs, reports
    status through the API, heartbeats the Node object."""

    def __init__(
        self,
        store: st.Store,
        node_name: str,
        checkpoint_path: Optional[str] = None,
        tick: float = 0.05,
        heartbeat_interval: float = 10.0,
        register: bool = False,
        cpu_milli: int = 32000,
        mem: int = 64 * (1 << 30),
        pods_cap: int = 110,
    ):
        self.store = store
        self.node_name = node_name
        self.tick = tick
        self.heartbeat_interval = heartbeat_interval
        self.checkpoint_path = checkpoint_path
        self._workers: Dict[str, _PodWorker] = {}
        # pod keys the heartbeat thread asked the sync loop to evict
        # (pressure eviction); consumed by _advance on the tick thread
        self._evict_requests: set = set()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._ip_counter = 0
        self._register = register
        self._caps = (cpu_milli, mem, pods_cap)
        if checkpoint_path and os.path.exists(checkpoint_path):
            self._load_checkpoint()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "NodeAgent":
        if self._register:
            self._register_node()
        t = threading.Thread(
            target=self._sync_loop, name=f"agent-{self.node_name}", daemon=True
        )
        t.start()
        self._threads.append(t)
        t = threading.Thread(
            target=self._heartbeat_loop,
            name=f"agent-hb-{self.node_name}",
            daemon=True,
        )
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    def _register_node(self) -> None:
        cpu, mem, pods = self._caps
        node = api.Node(
            meta=api.ObjectMeta(
                name=self.node_name,
                namespace="",
                labels={api.LABEL_HOSTNAME: self.node_name},
            ),
            status=api.NodeStatus(
                allocatable={api.CPU: cpu, api.MEMORY: mem, api.PODS: pods},
                capacity={api.CPU: cpu, api.MEMORY: mem, api.PODS: pods},
            ),
        )
        try:
            self.store.create(node)
        except st.AlreadyExists:
            pass

    # -- the sync loop (kubelet.go:2338) -------------------------------------

    def _sync_loop(self) -> None:
        pods, rv = self.store.list("Pod")
        for p in pods:
            self._observe(p)
        w = self.store.watch("Pod", from_rv=rv)
        try:
            while not self._stop.is_set():
                if w.stopped:
                    # expired as a slow watcher (coalescing overflow):
                    # relist + rewatch (reflector contract), reconciling
                    # the worker set
                    w.stop()
                    pods, rv = self.store.list("Pod")
                    mine = set()
                    for p in pods:
                        self._observe(p)
                        if p.spec.node_name == self.node_name:
                            mine.add(_key(p))
                    for key in list(self._workers):
                        if key not in mine:
                            self._workers.pop(key, None)
                    w = self.store.watch("Pod", from_rv=rv)
                # drain config events, then advance every worker one step
                while True:
                    ev = w.get(timeout=0.0)
                    if ev is None:
                        break
                    if ev.type == st.DELETED:
                        self._workers.pop(_key(ev.obj), None)
                    else:
                        self._observe(ev.obj)
                now = time.time()
                for key in list(self._workers):
                    try:
                        self._advance(key, now)
                    except st.NotFound:
                        self._workers.pop(key, None)
                    except st.Conflict:
                        pass  # re-read next tick
                self._checkpoint()
                self._stop.wait(self.tick)
        finally:
            w.stop()

    def _observe(self, pod: api.Pod) -> None:
        if pod.spec.node_name != self.node_name:
            return
        key = _key(pod)
        worker = self._workers.get(key)
        if worker is None:
            worker = _PodWorker(pod, time.time())
            # a checkpointed restart resumes counts for pods we had
            saved = getattr(self, "_saved", {}).pop(key, None)
            if saved:
                worker.load(saved)
            self._workers[key] = worker
        worker.pod = pod
        if pod.meta.deletion_timestamp is not None and worker.state not in (
            "terminating",
            "terminal",
        ):
            worker.state = "terminating"
            worker.terminating_since = time.time()

    # -- FSM ----------------------------------------------------------------

    def _advance(self, key: str, now: float) -> None:
        worker = self._workers[key]
        pod = worker.pod
        ann = pod.meta.annotations
        if worker.state == "terminal":
            return
        if key in self._evict_requests and worker.state in (
            "starting", "running"
        ):
            self._evict_requests.discard(key)
            self._evict(worker)
            return
        if worker.state == "terminating":
            grace = min(
                float(pod.spec.termination_grace_period_seconds),
                _grace_override(ann),
            )
            if now - (worker.terminating_since or now) >= grace:
                self._finish_termination(worker)
            return
        if worker.state == "starting":
            # add our finalizer once so deletion becomes two-phase
            if FINALIZER not in pod.meta.finalizers:
                self._mutate(worker, add_finalizer=True)
                return
            delay = max(
                (c.startup_probe.initial_delay_seconds
                 for c in pod.spec.containers if c.startup_probe),
                default=0.0,
            )
            if now - worker.started_at >= delay:
                worker.state = "running"
                self._mutate(worker, running=True)
            return
        # running: scripted exit?
        exit_after = ann.get(ANN_EXIT_AFTER)
        if exit_after is not None and now - worker.started_at >= float(exit_after):
            self._container_exit(worker, int(ann.get(ANN_EXIT_CODE, "0")))
            return
        # liveness (prober/worker.go): scripted failure accrues toward
        # failureThreshold, then restarts per policy
        probe = next(
            (c.liveness_probe for c in pod.spec.containers if c.liveness_probe),
            None,
        )
        threshold = probe.failure_threshold if probe else 3
        if ann.get(ANN_FAIL_LIVENESS) == "true":
            worker.live_fails += 1
            if worker.live_fails >= threshold:
                worker.live_fails = 0
                self._restart_or_fail(worker, exit_code=137)
                return
        else:
            worker.live_fails = 0
        # readiness gates the Ready condition
        desired_ready = ann.get(ANN_FAIL_READINESS) != "true"
        if desired_ready != worker.ready:
            worker.ready = desired_ready
            self._mutate(worker)

    def _restart_or_fail(self, worker: _PodWorker, exit_code: int) -> None:
        pod = worker.pod
        policy = pod.spec.restart_policy
        if policy == "Always" or (policy == "OnFailure" and exit_code != 0):
            # a spec with no containers (hollow pods created without the
            # admission defaulter) still has one implicit container
            for c in pod.spec.containers or [api.Container()]:
                worker.restart_counts[c.name] = (
                    worker.restart_counts.get(c.name, 0) + 1
                )
            worker.state = "starting"
            worker.started_at = time.time()
            worker.ready = False
            self._mutate(worker)
        else:
            self._terminal(worker, "Failed" if exit_code else "Succeeded")

    def _container_exit(self, worker: _PodWorker, exit_code: int) -> None:
        # policy arbitration lives in _restart_or_fail: Always restarts
        # any exit, OnFailure restarts non-zero, otherwise terminal phase
        self._restart_or_fail(worker, exit_code)

    def _evict(self, worker: _PodWorker) -> None:
        """Pressure eviction on the sync-loop thread: Failed phase +
        DisruptionTarget condition (the signal controllers recreate
        from), finalizer released so deletion is not blocked."""
        worker.phase = "Failed"
        worker.state = "terminal"
        worker.ready = False
        try:
            pod = self.store.get(
                "Pod", worker.pod.meta.name, worker.pod.meta.namespace
            )
            pod.status.phase = "Failed"
            pod.status.conditions = [
                c for c in pod.status.conditions
                if c.get("type") != "DisruptionTarget"
            ] + [{
                "type": "DisruptionTarget",
                "status": "True",
                "reason": "TerminationByKubelet",
                "message": "memory pressure eviction",
            }]
            if FINALIZER in pod.meta.finalizers:
                pod.meta.finalizers.remove(FINALIZER)
            self.store.update(pod, force=True, copy_result=False)
            worker.pod = pod
        except (st.NotFound, st.Conflict):
            pass

    def _terminal(self, worker: _PodWorker, phase: str) -> None:
        worker.state = "terminal"
        worker.phase = phase
        worker.ready = False
        # terminal pods must not block deletion: drop our finalizer now
        self._mutate(worker, drop_finalizer=True)

    def _finish_termination(self, worker: _PodWorker) -> None:
        """Grace elapsed: release the finalizer; the store completes the
        two-phase delete and the DELETED event untracks the worker."""
        self._mutate(worker, drop_finalizer=True)

    # -- status writes ------------------------------------------------------

    def _mutate(
        self,
        worker: _PodWorker,
        add_finalizer: bool = False,
        drop_finalizer: bool = False,
        running: bool = False,
    ) -> None:
        pod = self.store.get(
            "Pod", worker.pod.meta.name, worker.pod.meta.namespace
        )
        if add_finalizer and FINALIZER not in pod.meta.finalizers:
            pod.meta.finalizers.append(FINALIZER)
        if drop_finalizer and FINALIZER in pod.meta.finalizers:
            pod.meta.finalizers.remove(FINALIZER)
        if running:
            pod.status.phase = "Running"
            if not pod.status.pod_ip:
                pod.status.pod_ip = self._alloc_ip(worker)
            pod.status.host_ip = self._node_ip()
        if worker.phase:
            pod.status.phase = worker.phase
        pod.status.restart_counts = dict(worker.restart_counts)
        conds = [c for c in pod.status.conditions if c.get("type") != "Ready"]
        conds.append(
            {
                "type": "Ready",
                "status": "True" if worker.ready else "False",
                "lastTransitionTime": time.time(),
            }
        )
        pod.status.conditions = conds
        updated = self.store.update(pod, force=True)
        worker.pod = updated

    def _alloc_ip(self, worker: _PodWorker) -> str:
        self._ip_counter += 1
        h = zlib.crc32(self.node_name.encode()) % 250
        return f"10.88.{h}.{(self._ip_counter % 253) + 1}"

    def _node_ip(self) -> str:
        h = zlib.crc32(self.node_name.encode())
        return f"10.64.{(h >> 8) % 256}.{h % 256}"

    # -- heartbeats ----------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            try:
                node = self.store.get("Node", self.node_name, namespace="")
                node.meta.annotations["agent/heartbeat"] = str(time.time())
                conds = [
                    c for c in node.status.conditions
                    if c.get("type") != "Ready"
                ]
                conds.append({"type": "Ready", "status": "True"})
                node.status.conditions = conds
                self.store.update(node, force=True, copy_result=False)
                self._check_pressure(node)
            except st.NotFound:
                pass
            self._publish_metrics()

    def _check_pressure(self, node: api.Node) -> None:
        """Eviction manager (pkg/kubelet/eviction): under node pressure
        (hollow signal: the memory-pressure annotation) evict the
        lowest-priority running pod per sync — phase Failed with the
        Evicted reason, exactly what controllers react to by
        recreating elsewhere.  One victim per pass (the reference's
        single-eviction cadence) so pressure relief is observable
        between kills."""
        if node.meta.annotations.get(
            "agent.kubernetes.io/memory-pressure"
        ) != "true":
            return
        # only REQUEST the eviction here: worker state and pod status
        # belong to the sync-loop thread — a concurrent _mutate would
        # otherwise race this write and resurrect the pod as Running
        # with the terminal worker stranded
        victims = sorted(
            (
                w for w in self._workers.values()
                if w.state in ("starting", "running")
            ),
            key=lambda w: (w.pod.spec.priority, w.pod.meta.name),
        )
        if victims:
            self._evict_requests.add(_key(victims[0].pod))

    def _publish_metrics(self) -> None:
        """PodMetrics for each running pod (the metrics-server pipeline
        the HPA consumes).  Usage comes from the cpu-usage annotation
        (scriptable load) or defaults to ~60% of the pod's request."""
        for worker in list(self._workers.values()):
            pod = worker.pod
            if worker.state != "running":
                continue
            ann = pod.meta.annotations
            if "agent.kubernetes.io/cpu-usage" in ann:
                cpu = int(float(ann["agent.kubernetes.io/cpu-usage"]))
            else:
                req = pod.resource_requests().get(api.CPU, 100)
                cpu = int(req * 0.6)
            m = api.PodMetrics(
                meta=api.ObjectMeta(
                    name=pod.meta.name, namespace=pod.meta.namespace
                ),
                usage={api.CPU: cpu},
                timestamp=time.time(),
            )
            try:
                self.store.create(m)
            except st.AlreadyExists:
                try:
                    cur = self.store.get(
                        "PodMetrics", pod.meta.name, pod.meta.namespace
                    )
                    cur.usage = m.usage
                    cur.timestamp = m.timestamp
                    self.store.update(cur, force=True, copy_result=False)
                except st.NotFound:
                    pass

    # -- checkpoint (checkpoint_manager.go:36) --------------------------------

    def _checkpoint(self) -> None:
        if not self.checkpoint_path:
            return
        doc = {
            "node": self.node_name,
            "ip_counter": self._ip_counter,
            "pods": {k: w.to_dict() for k, w in self._workers.items()},
        }
        blob = json.dumps(doc)
        if blob == getattr(self, "_last_checkpoint", None):
            return
        tmp = self.checkpoint_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(blob)
        os.replace(tmp, self.checkpoint_path)
        self._last_checkpoint = blob

    def _load_checkpoint(self) -> None:
        try:
            with open(self.checkpoint_path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        if doc.get("node") != self.node_name:
            return
        self._ip_counter = int(doc.get("ip_counter", 0))
        # pods re-adopt their saved worker state on first observation
        self._saved: Dict[str, Dict[str, Any]] = dict(doc.get("pods", {}))


def _key(pod: api.Pod) -> str:
    return f"{pod.meta.namespace}/{pod.meta.name}"


def _grace_override(ann: Dict[str, str]) -> float:
    v = ann.get("agent.kubernetes.io/grace-seconds")
    return float(v) if v else float("inf")
