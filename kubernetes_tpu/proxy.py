"""Service proxy: the VIP -> backend dataplane table.

Reference: pkg/proxy/iptables/proxier.go:142,796 — kube-proxy watches
Services + EndpointSlices and compiles them into kernel rules that
rewrite VIP:port to a backend pod.  An in-process control plane has no
kernel to program, but the load-bearing artifact is the RULE TABLE and
its maintenance: this module keeps a versioned, atomically-swapped
resolution table from the same inputs (the syncProxyRules analogue) and
answers "what backs this VIP" — round-robin across ready endpoints,
ClientIP session affinity when the Service asks for it, and node-local
preference for (the semantics of) internalTrafficPolicy=Local.

`resolve()` is the dataplane lookup a connection would hit; `rules()`
dumps the whole table (the iptables-save analogue) for inspection and
tests.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from .api import store as st
from .api import types as api
from .client.informers import InformerFactory


class _ServiceRules:
    """One service's compiled rules: VIP:port -> backend list."""

    def __init__(self, svc: api.Service):
        self.cluster_ip = svc.spec.cluster_ip
        self.session_affinity = svc.spec.session_affinity
        # port -> [(pod_ip, target_port, node_name)], ready only
        self.by_port: Dict[int, List[Tuple[str, int, str]]] = {}


class ServiceProxy:
    """Watches Services + EndpointSlices; maintains the swap-on-write
    rule table (proxier.go syncProxyRules: full recompute per change,
    readers never see a partial table)."""

    def __init__(self, store: st.Store, node_name: str = ""):
        self.store = store
        self.node_name = node_name  # for Local traffic preference
        self.informers = InformerFactory(store)
        self._table: Dict[Tuple[str, int], _ServiceRules] = {}
        self._rr: Dict[Tuple[str, int], int] = {}
        self._affinity: Dict[Tuple[str, str, int], Tuple[str, int]] = {}
        self._lock = threading.Lock()
        # serializes whole syncs (list + compile + swap): the Service and
        # EndpointSlice informers run handlers on separate threads, and
        # an older snapshot must never be swapped in after a newer one
        # (the reference funnels syncProxyRules through one runner)
        self._sync_lock = threading.Lock()
        self.syncs = 0

    def start(self) -> "ServiceProxy":
        for kind in ("Service", "EndpointSlice"):
            inf = self.informers.informer(kind)
            inf.add_handler(lambda *_a: self._sync())
            inf.start()
        self.informers.wait_for_sync()
        self._sync()
        return self

    def stop(self) -> None:
        self.informers.stop()

    # -- rule compilation (syncProxyRules) ----------------------------------

    def _sync(self) -> None:
        with self._sync_lock:
            self._sync_locked()

    def _sync_locked(self) -> None:
        services = self.informers.informer("Service").list()
        slices = self.informers.informer("EndpointSlice").list()
        by_service: Dict[Tuple[str, str], List[api.EndpointSlice]] = {}
        for s in slices:
            name = s.meta.labels.get(api.LABEL_SERVICE_NAME)
            if name:
                by_service.setdefault((s.meta.namespace, name), []).append(s)
        table: Dict[Tuple[str, int], _ServiceRules] = {}
        for svc in services:
            vip = svc.spec.cluster_ip
            if not vip or vip == "None":
                continue  # headless: DNS answers, the proxy doesn't
            rules = _ServiceRules(svc)
            eps = by_service.get((svc.meta.namespace, svc.meta.name), [])
            for port in svc.spec.ports:
                backends: List[Tuple[str, int, str]] = []
                for s in eps:
                    target = next(
                        (p.port for p in s.ports if p.name == port.name),
                        port.target_port or port.port,
                    )
                    for e in s.endpoints:
                        if not e.conditions.ready or not e.addresses:
                            continue
                        backends.append(
                            (e.addresses[0], target, e.node_name)
                        )
                backends.sort()
                rules.by_port[port.port] = backends
                table[(vip, port.port)] = rules
        valid = {
            (ip, tp)
            for r in table.values()
            for bs in r.by_port.values()
            for ip, tp, _n in bs
        }
        with self._lock:
            self._table = table  # atomic swap; prune dead affinities
            self._affinity = {
                k: v for k, v in self._affinity.items() if v in valid
            }
            self.syncs += 1

    # -- the dataplane lookup -----------------------------------------------

    def resolve(
        self, vip: str, port: int, client_ip: str = "", local_only: bool = False
    ) -> Optional[Tuple[str, int]]:
        """(backend_ip, backend_port) for a connection to VIP:port, or
        None (no service / no ready backends — the reference's REJECT
        rule).  ClientIP affinity sticks a client to its backend while
        that backend stays ready."""
        with self._lock:
            rules = self._table.get((vip, port))
            if rules is None:
                return None
            backends = rules.by_port.get(port, [])
            if local_only and self.node_name:
                backends = [
                    b for b in backends if b[2] == self.node_name
                ] or backends
            if not backends:
                return None
            if rules.session_affinity == "ClientIP" and client_ip:
                key = (client_ip, vip, port)
                prior = self._affinity.get(key)
                if prior is not None and any(
                    (ip, tp) == prior for ip, tp, _n in backends
                ):
                    return prior
            rr_key = (vip, port)
            i = self._rr.get(rr_key, 0)
            ip, tport, _node = backends[i % len(backends)]
            self._rr[rr_key] = i + 1
            if rules.session_affinity == "ClientIP" and client_ip:
                self._affinity[(client_ip, vip, port)] = (ip, tport)
            return ip, tport

    def rules(self) -> Dict[str, List[str]]:
        """Human-readable dump (iptables-save analogue)."""
        with self._lock:
            out: Dict[str, List[str]] = {}
            for (vip, port), r in sorted(self._table.items()):
                out[f"{vip}:{port}"] = [
                    f"-> {ip}:{tp} (node {node or '?'})"
                    for ip, tp, node in r.by_port.get(port, [])
                ]
            return out
