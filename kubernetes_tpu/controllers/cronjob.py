"""CronJob controller: Jobs on a cron schedule.

Reference: pkg/controller/cronjob/cronjob_controllerv2.go — each sync
computes the schedule's most recent fire time since lastScheduleTime;
if one is due, a Job named <cron>-<unix-minute> is created subject to
the concurrency policy (Allow runs overlap, Forbid skips while one is
active, Replace deletes the running one first).  startingDeadlineSeconds
bounds how stale a missed fire may be and still run.  The cron grammar
is the standard 5-field subset: `*`, `*/step`, lists, ranges.
"""

from __future__ import annotations

import threading
import time
from typing import List, NamedTuple, Optional

from ..api import store as st
from ..api import types as api
from .base import Controller, split_key

_FIELDS = (  # (min, max) per cron field
    (0, 59),   # minute
    (0, 23),   # hour
    (1, 31),   # day of month
    (1, 12),   # month
    (0, 6),    # day of week (0 = Sunday)
)


class CronSchedule(NamedTuple):
    fields: List[set]
    dom_any: bool  # day-of-month field was "*"
    dow_any: bool  # day-of-week field was "*"


def parse_cron(expr: str) -> CronSchedule:
    parts = expr.split()
    if len(parts) != 5:
        raise ValueError(f"cron {expr!r}: want 5 fields, got {len(parts)}")
    out = []
    for raw, (lo, hi) in zip(parts, _FIELDS):
        allowed = set()
        for piece in raw.split(","):
            body, _, step_s = piece.partition("/")
            step = int(step_s) if step_s else 1
            if step <= 0:
                raise ValueError(f"cron {expr!r}: step must be positive")
            if body in ("*", ""):
                start, end = lo, hi
            elif "-" in body:
                a, b = body.split("-", 1)
                start, end = int(a), int(b)
            else:
                start = end = int(body)
            if not (lo <= start <= end <= hi):
                raise ValueError(f"cron {expr!r}: {piece!r} out of range")
            allowed.update(range(start, end + 1, step))
        out.append(allowed)
    return CronSchedule(
        out, dom_any=parts[2] == "*", dow_any=parts[4] == "*"
    )


def matches(sched: CronSchedule, t: float, tz: str = None) -> bool:
    """tz None = controller-local wall time (the reference's default —
    with its documented DST double-fire/skip caveat); otherwise any IANA
    zone name resolved via zoneinfo (batch/v1 spec.timeZone — a named
    zone silently falling back to local time would fire hours wrong,
    the one failure the field exists to prevent; unknown names raise)."""
    fields = sched.fields
    if tz:
        from datetime import datetime, timezone
        from zoneinfo import ZoneInfo

        dt = datetime.fromtimestamp(t, timezone.utc).astimezone(ZoneInfo(tz))
        dow = (dt.weekday() + 1) % 7
        dom_ok = dt.day in fields[2]
        dow_ok = dow in fields[4]
        if sched.dom_any or sched.dow_any:
            day_ok = dom_ok and dow_ok
        else:
            day_ok = dom_ok or dow_ok
        return (
            dt.minute in fields[0]
            and dt.hour in fields[1]
            and dt.month in fields[3]
            and day_ok
        )
    lt = time.localtime(t)
    dow = (lt.tm_wday + 1) % 7  # tm_wday: Monday=0; cron: Sunday=0
    dom_ok = lt.tm_mday in fields[2]
    dow_ok = dow in fields[4]
    # standard cron: when BOTH day fields are restricted, they OR
    # (vixie-cron semantics — '0 0 13 * 5' fires the 13th OR Fridays)
    if sched.dom_any or sched.dow_any:
        day_ok = dom_ok and dow_ok
    else:
        day_ok = dom_ok or dow_ok
    return (
        lt.tm_min in fields[0]
        and lt.tm_hour in fields[1]
        and lt.tm_mon in fields[3]
        and day_ok
    )


def most_recent_fire(
    fields: CronSchedule, since: float, now: float, tz: str = None
) -> Optional[float]:
    """The latest minute boundary in (since, now] matching the schedule
    (getMostRecentScheduleTime).  Scans minute-by-minute, capped to a
    day — a gap wider than that reports the newest match only, like the
    reference's 'too many missed start times' clamp."""
    start_min = int(since // 60) + 1
    now_min = int(now // 60)
    start_min = max(start_min, now_min - 24 * 60)
    for m in range(now_min, start_min - 1, -1):
        t = m * 60.0
        if matches(fields, t, tz):
            return t
    return None


class CronJobController(Controller):
    KIND = "CronJob"
    RESYNC_SECONDS = 10.0

    def __init__(self, store, informers, workers: int = 2, clock=time.time):
        super().__init__(store, informers, workers=workers)
        self.clock = clock

    def register(self) -> None:
        self.informers.informer("CronJob").add_handler(self._on_cron)
        self.informers.informer("Job").add_handler(self._on_job)
        self._tick_stop = threading.Event()
        self._ticker = threading.Thread(
            target=self._tick_loop, name="cronjob-ticker", daemon=True
        )
        self._ticker.start()

    def stop(self) -> None:
        if hasattr(self, "_tick_stop"):
            self._tick_stop.set()
        super().stop()

    def _tick_loop(self) -> None:
        # time-driven requeue: cron fires without object events
        while not self._tick_stop.wait(self.RESYNC_SECONDS):
            for cj in self.informers.informer("CronJob").list():
                self.enqueue(cj)

    def _on_cron(self, typ: str, obj, old) -> None:
        if typ != st.DELETED:
            self.enqueue(obj)

    def _on_job(self, typ: str, job, old) -> None:
        self.enqueue_owner(job, "CronJob")

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        try:
            cj = self.store.get("CronJob", name, namespace)
        except st.NotFound:
            return
        self._reap_finished_actives(cj)
        if cj.spec.suspend:
            return
        fields = parse_cron(cj.spec.schedule)
        now = self.clock()
        since = cj.status.last_schedule_time or (now - 60)
        fire = most_recent_fire(fields, since, now, cj.spec.time_zone)
        if fire is None:
            return
        deadline = cj.spec.starting_deadline_seconds
        if deadline is not None and now - fire > deadline:
            return  # missed too long ago (startingDeadlineSeconds)
        active = self._active_jobs(cj)
        if active:
            if cj.spec.concurrency_policy == "Forbid":
                return
            if cj.spec.concurrency_policy == "Replace":
                for j in active:
                    try:
                        self.store.delete("Job", j.meta.name, namespace)
                    except st.NotFound:
                        pass
        job_name = f"{name}-{int(fire // 60)}"
        job = api.Job(
            meta=api.ObjectMeta(
                name=job_name,
                namespace=namespace,
                owner_references=[
                    api.OwnerReference(
                        kind="CronJob", name=name,
                        uid=cj.meta.uid, controller=True,
                    )
                ],
            ),
            spec=api.clone(cj.spec.job_template),
        )
        try:
            self.store.create(job)
        except st.AlreadyExists:
            pass  # this fire time already ran
        fresh = self.store.get("CronJob", name, namespace)
        fresh.status.last_schedule_time = fire
        if job_name not in fresh.status.active:
            fresh.status.active.append(job_name)
        self.store.update(fresh)

    def _active_jobs(self, cj: api.CronJob) -> List[api.Job]:
        out = []
        for j in self.informers.informer("Job").list():
            if j.meta.namespace != cj.meta.namespace:
                continue
            refs = [
                r for r in j.meta.owner_references
                if r.kind == "CronJob" and r.name == cj.meta.name
            ]
            if refs and j.status.completion_time is None:
                out.append(j)
        return out

    def _reap_finished_actives(self, cj: api.CronJob) -> None:
        still = [j.meta.name for j in self._active_jobs(cj)]
        if set(cj.status.active) == set(still):
            return
        try:
            fresh = self.store.get("CronJob", cj.meta.name, cj.meta.namespace)
        except st.NotFound:
            return
        fresh.status.active = still
        self.store.update(fresh)
