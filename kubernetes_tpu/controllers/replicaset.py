"""ReplicaSet controller: keep spec.replicas pods alive from the template.

Reference: pkg/controller/replicaset/replica_set.go — syncReplicaSet
diffs filtered pods vs *(rs.Spec.Replicas) and calls
slowStartBatch(create) / rank-and-delete; ours creates/deletes through
the store in one reconcile step (no slow-start: the in-memory API
doesn't rate-limit).  Deletion preference mirrors
getPodsToDelete/ActivePodsWithRanks: pending (unscheduled) pods go
before scheduled ones, younger before older.
"""

from __future__ import annotations

import itertools

from ..api import store as st
from ..api import types as api
from .base import Controller, split_key

_suffix = itertools.count(1)


def pod_from_template(
    template: api.PodTemplateSpec, owner, name: str
) -> api.Pod:
    pod = api.Pod(
        meta=api.ObjectMeta(
            name=name,
            namespace=owner.meta.namespace,
            labels=dict(template.meta.labels),
            owner_references=[
                api.OwnerReference(
                    kind=owner.KIND,
                    name=owner.meta.name,
                    uid=owner.meta.uid,
                    controller=True,
                )
            ],
        ),
        spec=api.clone(template.spec),
    )
    return pod


class ReplicaSetController(Controller):
    KIND = "ReplicaSet"

    def register(self) -> None:
        self.informers.informer("ReplicaSet").add_handler(self._on_rs)
        self.informers.informer("Pod").add_handler(self._on_pod)

    def _on_rs(self, typ: str, rs, old) -> None:
        # DELETED included: sync's NotFound path cascade-deletes the
        # owned pods (the GC controller's job in the reference)
        self.enqueue(rs)

    def _on_pod(self, typ: str, pod: api.Pod, old) -> None:
        ref = None
        for r in pod.meta.owner_references:
            if r.controller and r.kind == self.KIND:
                ref = r
        if ref is not None:
            key = f"{pod.meta.namespace}/{ref.name}"
            if typ == st.ADDED:
                self.expectations.creation_observed(key)
            elif typ == st.DELETED:
                self.expectations.deletion_observed(key)
            self.queue.add(key)

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        try:
            rs = self.store.get("ReplicaSet", name, namespace)
        except st.NotFound:
            # RS deleted: the garbage collector cascades to owned pods
            # via ownerReferences (controllers/garbagecollector.py) —
            # deleting here too would bypass the orphan annotation
            self.expectations.forget(key)
            return
        all_owned = self.pods_owned_by(namespace, "ReplicaSet", name)
        pods = [
            p for p in all_owned
            if p.status.phase not in ("Succeeded", "Failed")
        ]
        # Only manage replicas once prior creates/deletes are OBSERVED in
        # the informer cache (ControllerExpectations) — counting early
        # double-provisions, since fresh names defeat AlreadyExists.
        if self.expectations.satisfied(key):
            diff = rs.spec.replicas - len(pods)
            if diff > 0:
                self.expectations.expect_creations(key, diff)
                for _ in range(diff):
                    pod = pod_from_template(
                        rs.spec.template, rs,
                        f"{name}-{next(_suffix):05d}",
                    )
                    try:
                        self.store.create(pod)
                    except st.AlreadyExists:  # name race: retry next sync
                        self.expectations.creation_observed(key)
                        self.queue.add(key)
            elif diff < 0:
                # prefer deleting unscheduled pods (ActivePodsWithRanks)
                victims = sorted(
                    pods,
                    key=lambda p: (bool(p.spec.node_name), -p.meta.resource_version),
                )[: -diff]
                self.expectations.expect_deletions(key, len(victims))
                for pod in victims:
                    try:
                        self.store.delete("Pod", pod.meta.name, namespace)
                    except st.NotFound:
                        self.expectations.deletion_observed(key)
        # status from the in-hand pod list; write ONLY on change (an
        # unconditional update would MODIFIED-event this same key into a
        # permanent self-triggering reconcile loop)
        ready = sum(1 for p in pods if p.spec.node_name)
        if (
            rs.status.replicas != len(pods)
            or rs.status.ready_replicas != ready
            or rs.status.observed_generation != rs.meta.generation
        ):
            rs.status.replicas = len(pods)
            rs.status.ready_replicas = ready
            rs.status.observed_generation = rs.meta.generation
            self.store.update(rs)
