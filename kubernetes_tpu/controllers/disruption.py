"""Disruption controller: maintains PodDisruptionBudget status.

Reference: pkg/controller/disruption/disruption.go — watches PDBs and
pods, recomputes expectedPods / currentHealthy / desiredHealthy /
disruptionsAllowed on every relevant event.  Preemption consults
status.disruptions_allowed when ranking victims
(framework/preemption/preemption.go:290 filterPodsWithPDBViolation).

Healthy = the Ready condition when a node agent reports one (matching
the reference's IsPodReady check, disruption.go:910), falling back to
Running phase for hollow nodes with no agent.  desiredHealthy:
  minAvailable set   -> minAvailable
  maxUnavailable set -> expectedPods - maxUnavailable
"""

from __future__ import annotations

from ..api import store as st
from ..api import types as api
from .base import Controller, obj_key, split_key


class DisruptionController(Controller):
    KIND = "PodDisruptionBudget"

    def register(self) -> None:
        self.informers.informer("PodDisruptionBudget").add_handler(
            self._on_pdb
        )
        self.informers.informer("Pod").add_handler(self._on_pod)

    def _on_pdb(self, typ: str, pdb: api.PodDisruptionBudget, old) -> None:
        if typ != st.DELETED:
            self.enqueue(pdb)

    def _on_pod(self, typ: str, pod: api.Pod, old) -> None:
        # any pod event can change a matching budget's health counts
        for pdb in self.informers.informer("PodDisruptionBudget").list():
            if pdb.matches(pod) or (old is not None and pdb.matches(old)):
                self.queue.add(obj_key(pdb))

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        try:
            pdb = self.store.get("PodDisruptionBudget", name, namespace)
        except KeyError:
            return
        pods = [
            p
            for p in self.informers.informer("Pod").list()
            if pdb.matches(p)
        ]
        expected = len(pods)
        healthy = sum(
            1
            for p in pods
            if p.status.phase == "Running" and api.pod_is_ready(p)
        )
        if pdb.spec.min_available is not None:
            desired = min(pdb.spec.min_available, expected)
        elif pdb.spec.max_unavailable is not None:
            desired = max(expected - pdb.spec.max_unavailable, 0)
        else:
            desired = expected
        allowed = max(healthy - desired, 0)
        status = pdb.status
        if (
            status.expected_pods == expected
            and status.current_healthy == healthy
            and status.desired_healthy == desired
            and status.disruptions_allowed == allowed
        ):
            return
        pdb.status = api.PodDisruptionBudgetStatus(
            disruptions_allowed=allowed,
            current_healthy=healthy,
            desired_healthy=desired,
            expected_pods=expected,
        )
        self.store.update(pdb)
