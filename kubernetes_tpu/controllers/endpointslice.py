"""Endpoint controllers: materialise "what backs this Service".

Reference: pkg/controller/endpointslice (reconciler.go, the slice
packing + minimal-write logic) and pkg/controller/endpoint
(endpoints_controller.go, the legacy aggregate object).  One controller
here maintains BOTH outputs from one computed backend set — the two
reference controllers independently recompute identical pod→service
matches; folding them halves the informer work at kubemark scale.

Shape of the reconcile:
  pod event  -> match the ONE changed pod against the namespace's
                services (O(services-in-ns), the reference's
                getPodServiceMemberships) -> enqueue those services
  svc event  -> enqueue
  sync(svc)  -> desired backends = ready/serving pods matching the
                selector, sorted -> packed into EndpointSlices of
                <=100 endpoints -> diffed against owned slices with
                create/update/delete keeping unchanged slices
                untouched (one pod's readiness flip rewrites one
                slice, not the whole set) -> legacy Endpoints object
                rewritten only when its content changed.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..api import store as st
from ..api import types as api
from .base import Controller, split_key

MAX_ENDPOINTS_PER_SLICE = 100  # discovery.k8s.io default


def _slice_index(name: str) -> int:
    tail = name.rsplit("-", 1)[-1]
    return int(tail) if tail.isdigit() else 0


def _service_key_of_slice(s: api.EndpointSlice) -> Optional[str]:
    name = s.meta.labels.get(api.LABEL_SERVICE_NAME)
    if not name:
        return None
    return f"{s.meta.namespace}/{name}"


def _resolve_target_port(port: api.ServicePort, pods: List[api.Pod]) -> int:
    """Numeric backend port for a ServicePort (FindPort,
    pkg/api/v1/pod/util.go): named targetPorts resolve against the
    first matching container port; numeric pass through; 0 falls back
    to the front port."""
    if port.target_port:
        return port.target_port
    if port.target_port_name:
        for pod in pods:
            for c in pod.spec.containers:
                for cp in c.ports:
                    if cp.name == port.target_port_name:
                        return cp.container_port
        return 0
    return port.port


def _endpoint_of(pod: api.Pod) -> api.Endpoint:
    return api.Endpoint(
        addresses=[pod.status.pod_ip] if pod.status.pod_ip else [],
        conditions=api.EndpointConditions(
            ready=api.pod_is_ready(pod),
            serving=api.pod_is_ready(pod),
            terminating=bool(pod.meta.deletion_timestamp),
        ),
        node_name=pod.spec.node_name,
        target_ref_name=pod.meta.name,
    )


def _endpoints_equal(a: api.Endpoint, b: api.Endpoint) -> bool:
    return (
        a.addresses == b.addresses
        and a.conditions == b.conditions
        and a.node_name == b.node_name
        and a.target_ref_name == b.target_ref_name
    )


class EndpointSliceController(Controller):
    KIND = "Service"

    def register(self) -> None:
        self.informers.informer("Service").add_handler(self._on_service)
        self.informers.informer("Pod").add_handler(self._on_pod)
        self.informers.informer("EndpointSlice").add_handler(self._on_slice)

    # -- event routing -----------------------------------------------------

    def _on_service(self, typ: str, svc: api.Service, old) -> None:
        self.enqueue(svc)

    def _on_pod(self, typ: str, pod: api.Pod, old) -> None:
        """Route the changed pod to the services it matches (and, on
        label change, the ones it STOPPED matching)."""
        for svc in self.informers.informer("Service").list():
            if svc.meta.namespace != pod.meta.namespace:
                continue
            sel = svc.spec.selector
            if not sel:
                continue
            labels = pod.meta.labels
            matches = all(labels.get(k) == v for k, v in sel.items())
            matched_old = (
                old is not None
                and all(old.meta.labels.get(k) == v for k, v in sel.items())
            )
            if matches or matched_old:
                self.enqueue(svc)

    def _on_slice(self, typ: str, s: api.EndpointSlice, old) -> None:
        # repair: a hand-deleted/mutated slice re-syncs its service
        key = _service_key_of_slice(s)
        if key:
            self.queue.add(key)

    # -- reconcile ---------------------------------------------------------

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        owned = [
            s
            for s in self.informers.informer("EndpointSlice").list()
            if s.meta.namespace == namespace
            and s.meta.labels.get(api.LABEL_SERVICE_NAME) == name
        ]
        try:
            svc = self.store.get("Service", name, namespace)
        except st.NotFound:
            # service gone: reap its slices + legacy object
            for s in owned:
                self._delete_slice(s)
            try:
                self.store.delete("Endpoints", name, namespace)
            except st.NotFound:
                pass
            return
        if not svc.spec.selector or svc.spec.type == "ExternalName":
            return  # selector-less services are managed by their owner
        backends = self._backends(svc)
        ports = [
            api.EndpointPort(
                name=p.name,
                protocol=p.protocol,
                port=_resolve_target_port(p, backends),
            )
            for p in svc.spec.ports
        ]
        desired = [_endpoint_of(p) for p in backends]
        if not svc.spec.publish_not_ready_addresses:
            desired = [e for e in desired if e.addresses]
        self._reconcile_slices(svc, desired, ports, owned)
        self._reconcile_legacy(svc, backends, ports)

    def _backends(self, svc: api.Service) -> List[api.Pod]:
        sel = svc.spec.selector
        out = []
        for p in self.informers.informer("Pod").list():
            if p.meta.namespace != svc.meta.namespace:
                continue
            if p.status.phase in ("Succeeded", "Failed"):
                continue
            if all(p.meta.labels.get(k) == v for k, v in sel.items()):
                out.append(p)
        out.sort(key=lambda p: p.meta.name)
        return out

    # -- slice packing/diffing (reconciler.go) ------------------------------

    def _reconcile_slices(
        self,
        svc: api.Service,
        desired: List[api.Endpoint],
        ports: List[api.EndpointPort],
        owned: List[api.EndpointSlice],
    ) -> None:
        chunks: List[List[api.Endpoint]] = [
            desired[i : i + MAX_ENDPOINTS_PER_SLICE]
            for i in range(0, len(desired), MAX_ENDPOINTS_PER_SLICE)
        ] or [[]]
        # numeric suffix order (zero-padded names keep lexicographic ==
        # numeric, but sort numerically anyway for robustness): chunk i
        # must pair with slice i or >10-slice services rewrite most
        # slices per change
        owned.sort(key=lambda s: _slice_index(s.meta.name))
        # pair chunks with existing slices positionally (stable sort on
        # both sides keeps an unchanged prefix byte-identical); update
        # only pairs whose content differs
        for i, chunk in enumerate(chunks):
            if i < len(owned):
                s = owned[i]
                same = (
                    len(s.endpoints) == len(chunk)
                    and all(
                        _endpoints_equal(a, b)
                        for a, b in zip(s.endpoints, chunk)
                    )
                    and s.ports == ports
                )
                if not same:
                    # mutate a COPY: `s` is the shared informer-cache
                    # object; editing it in place would make a failed
                    # update look already-converged on retry
                    s = api.clone(s)
                    s.endpoints = chunk
                    s.ports = ports
                    self.store.update(s)
            else:
                fresh = api.EndpointSlice(
                    meta=api.ObjectMeta(
                        name=f"{svc.meta.name}-{i:04d}",
                        namespace=svc.meta.namespace,
                        labels={api.LABEL_SERVICE_NAME: svc.meta.name},
                        owner_references=[
                            api.OwnerReference(
                                kind="Service",
                                name=svc.meta.name,
                                uid=svc.meta.uid,
                                controller=True,
                            )
                        ],
                    ),
                    endpoints=chunk,
                    ports=ports,
                )
                try:
                    self.store.create(fresh)
                except st.AlreadyExists:
                    # informer cache lag: the slice exists but wasn't in
                    # `owned` yet — converge by overwriting its content
                    cur = self.store.get(
                        "EndpointSlice", fresh.meta.name, fresh.meta.namespace
                    )
                    cur.endpoints = chunk
                    cur.ports = ports
                    cur.meta.labels[api.LABEL_SERVICE_NAME] = svc.meta.name
                    self.store.update(cur, force=True)
        for s in owned[len(chunks):]:
            self._delete_slice(s)

    def _delete_slice(self, s: api.EndpointSlice) -> None:
        try:
            self.store.delete("EndpointSlice", s.meta.name, s.meta.namespace)
        except st.NotFound:
            pass

    # -- legacy Endpoints (endpoints_controller.go) -------------------------

    def _reconcile_legacy(
        self,
        svc: api.Service,
        backends: List[api.Pod],
        ports: List[api.EndpointPort],
    ) -> None:
        ready: List[api.EndpointAddress] = []
        not_ready: List[api.EndpointAddress] = []
        for p in backends:
            if not p.status.pod_ip:
                continue
            addr = api.EndpointAddress(
                ip=p.status.pod_ip,
                node_name=p.spec.node_name,
                target_ref_name=p.meta.name,
            )
            (ready if api.pod_is_ready(p) else not_ready).append(addr)
        subsets = (
            [
                api.EndpointSubset(
                    addresses=ready,
                    not_ready_addresses=not_ready,
                    ports=ports,
                )
            ]
            if (ready or not_ready)
            else []
        )
        try:
            cur = self.store.get("Endpoints", svc.meta.name, svc.meta.namespace)
            if cur.subsets != subsets:
                cur.subsets = subsets
                self.store.update(cur)
        except st.NotFound:
            self.store.create(
                api.Endpoints(
                    meta=api.ObjectMeta(
                        name=svc.meta.name,
                        namespace=svc.meta.namespace,
                        owner_references=[
                            api.OwnerReference(
                                kind="Service",
                                name=svc.meta.name,
                                uid=svc.meta.uid,
                                controller=True,
                            )
                        ],
                    ),
                    subsets=subsets,
                )
            )
