"""PersistentVolume controller: the binding/reclaim reconciler.

Reference: pkg/controller/volume/persistentvolume/pv_controller.go —
syncClaim (bind pending Immediate-mode claims to matching Available
volumes) and syncVolume (repair half-bound pairs; apply the reclaim
policy when a bound claim disappears).  The SCHEDULER owns
WaitForFirstConsumer binding (scheduler/volumebinding.py — topology
decides there); this controller owns everything that must work without
a pod: Immediate-mode claims bind as soon as a volume matches, crashed
half-bindings heal, and released volumes are retained or deleted per
their reclaim policy.
"""

from __future__ import annotations

from typing import List, Optional

from ..api import store as st
from ..api import types as api
from .base import Controller, split_key


class PersistentVolumeController(Controller):
    KIND = "PersistentVolume"
    NAME = "PersistentVolumeBinder"

    def register(self) -> None:
        self.informers.informer("PersistentVolume").add_handler(self._on_pv)
        self.informers.informer("PersistentVolumeClaim").add_handler(
            self._on_pvc
        )

    def _on_pv(self, typ: str, pv, old) -> None:
        if typ != st.DELETED:
            self.queue.add(f"pv||{pv.meta.name}")

    def _on_pvc(self, typ: str, pvc, old) -> None:
        if typ == st.DELETED:
            if pvc.spec.volume_name:
                # the bound volume must react (reclaim)
                self.queue.add(f"pv||{pvc.spec.volume_name}")
            else:
                # half-bound death: a PV may hold a dangling claim_ref
                # to this claim with the PVC side never written — scan
                # for it or a Delete-policy volume leaks forever
                self.queue.add(
                    f"scan|{pvc.meta.namespace}|{pvc.meta.name}"
                )
            return
        self.queue.add(f"pvc|{pvc.meta.namespace}|{pvc.meta.name}")

    def sync(self, key: str) -> None:
        what, namespace, name = key.split("|", 2)
        if what == "pvc":
            self._sync_claim(namespace, name)
        elif what == "scan":
            claim_key = f"{namespace}/{name}"
            for pv in self.informers.informer("PersistentVolume").list():
                if pv.spec.claim_ref == claim_key:
                    self.queue.add(f"pv||{pv.meta.name}")
        else:
            self._sync_volume(name)

    # -- syncClaim ----------------------------------------------------------

    def _binding_mode(self, pvc) -> str:
        sc = next(
            (
                c
                for c in self.informers.informer("StorageClass").list()
                if c.meta.name == pvc.spec.storage_class_name
            ),
            None,
        )
        return sc.volume_binding_mode if sc else api.VOLUME_BINDING_IMMEDIATE

    def _sync_claim(self, namespace: str, name: str) -> None:
        try:
            pvc = self.store.get("PersistentVolumeClaim", name, namespace)
        except st.NotFound:
            return
        if pvc.spec.volume_name:
            if pvc.status.phase != api.PVC_BOUND:
                pvc.status.phase = api.PVC_BOUND
                self.store.update(pvc, force=True)
            return
        if self._binding_mode(pvc) == api.VOLUME_BINDING_WAIT:
            return  # the scheduler binds at pod placement time
        key = f"{namespace}/{name}"
        pv = self._match(pvc, key)
        if pv is None:
            return
        # bind PV side first, then PVC (the same order prebind uses; a
        # crash in between heals via _sync_volume's repair half)
        fresh_pv = self.store.get("PersistentVolume", pv.meta.name)
        if fresh_pv.spec.claim_ref and fresh_pv.spec.claim_ref != key:
            return  # raced with another binder; resync will re-match
        fresh_pv.spec.claim_ref = key
        fresh_pv.spec.claim_uid = pvc.meta.uid
        fresh_pv.status.phase = api.PV_BOUND
        self.store.update(fresh_pv)
        pvc.spec.volume_name = pv.meta.name
        pvc.status.phase = api.PVC_BOUND
        self.store.update(pvc, force=True)

    def _match(self, pvc, claim_key: str) -> Optional[api.PersistentVolume]:
        """findMatchingVolume: smallest Available PV satisfying class,
        modes, and size (or one already claimRef'd to this PVC — the
        half-bound repair)."""
        want_modes = set(pvc.spec.access_modes)
        best = None
        for pv in self.informers.informer("PersistentVolume").list():
            if pv.spec.claim_ref == claim_key:
                return pv  # finish the half-bound pair
            if pv.spec.claim_ref or pv.status.phase != api.PV_AVAILABLE:
                continue
            if pv.spec.storage_class_name != pvc.spec.storage_class_name:
                continue
            if not want_modes.issubset(set(pv.spec.access_modes)):
                continue
            if pv.storage() < pvc.requested_storage():
                continue
            if best is None or pv.storage() < best.storage():
                best = pv
        return best

    # -- syncVolume ---------------------------------------------------------

    def _sync_volume(self, name: str) -> None:
        try:
            pv = self.store.get("PersistentVolume", name)
        except st.NotFound:
            return
        ref = pv.spec.claim_ref
        if not ref:
            return
        ns, _, claim_name = ref.partition("/")
        pvc = None
        try:
            pvc = self.store.get("PersistentVolumeClaim", claim_name, ns)
        except st.NotFound:
            pass
        if pvc is not None and pv.spec.claim_uid and (
            pvc.meta.uid != pv.spec.claim_uid
        ):
            # same NAME, different claim: the bound claim was deleted and
            # recreated — the new claim must not inherit the volume
            pvc = None
        if pvc is None:
            # claim gone: apply the reclaim policy
            if pv.spec.reclaim_policy == "Delete":
                try:
                    self.store.delete("PersistentVolume", name)
                except st.NotFound:
                    pass
            elif pv.status.phase != api.PV_RELEASED:
                pv.status.phase = api.PV_RELEASED
                self.store.update(pv, force=True)
            return
        if not pvc.spec.volume_name:
            # half-bound (crash between the two binding writes): finish
            # the PVC side (syncVolume's repair)
            pvc.spec.volume_name = name
            pvc.status.phase = api.PVC_BOUND
            self.store.update(pvc, force=True)
        if pv.status.phase != api.PV_BOUND:
            pv.status.phase = api.PV_BOUND
            self.store.update(pv, force=True)
