"""Controller manager: shared informers + the registered control loops.

Reference: cmd/kube-controller-manager/app/controllermanager.go:479-566
builds descriptors and starts each controller against one shared
informer factory; ours instantiates the implemented set and shares the
store's InformerFactory the same way.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Type

from ..api import store as st
from ..client.informers import InformerFactory
from .base import Controller
from .cronjob import CronJobController
from .daemonset import DaemonSetController
from .deployment import DeploymentController
from .disruption import DisruptionController
from .endpointslice import EndpointSliceController
from .garbagecollector import GarbageCollector
from .job import JobController
from .namespace import NamespaceController
from .podautoscaler import HorizontalPodAutoscalerController
from .podgc import PodGCController
from .pvcontroller import PersistentVolumeController
from .replicaset import ReplicaSetController
from .resourcequota import ResourceQuotaController
from .serviceaccount import ServiceAccountController, TTLAfterFinishedController
from .statefulset import StatefulSetController

DEFAULT_CONTROLLERS: List[Type[Controller]] = [
    ReplicaSetController,
    DeploymentController,
    JobController,
    DisruptionController,
    GarbageCollector,
    NamespaceController,
    StatefulSetController,
    DaemonSetController,
    CronJobController,
    EndpointSliceController,
    HorizontalPodAutoscalerController,
    ResourceQuotaController,
    ServiceAccountController,
    TTLAfterFinishedController,
    PersistentVolumeController,
    PodGCController,
]


class ControllerManager:
    def __init__(
        self,
        store: st.Store,
        controllers: Optional[List[Type[Controller]]] = None,
        workers: int = 2,
    ):
        self.store = store
        self.informers = InformerFactory(store)
        # keyed by NAME when a controller shares its primary KIND with
        # another (TTLAfterFinished also reconciles Jobs)
        self.controllers: Dict[str, Controller] = {
            getattr(cls, "NAME", cls.KIND): cls(
                store, self.informers, workers=workers
            )
            for cls in (controllers or DEFAULT_CONTROLLERS)
        }

    def start(self) -> "ControllerManager":
        # informers for every kind any controller watches
        for kind in (
            "Pod", "ReplicaSet", "Deployment", "Job", "PodDisruptionBudget",
            "Namespace", "StatefulSet", "DaemonSet", "CronJob", "Node",
            "Service", "EndpointSlice", "HorizontalPodAutoscaler",
            "PodMetrics", "ResourceQuota", "ServiceAccount",
            "PersistentVolume", "PersistentVolumeClaim", "StorageClass",
        ):
            self.informers.informer(kind).start()
        self.informers.wait_for_sync()
        for c in self.controllers.values():
            c.start()
        return self

    def stop(self) -> None:
        for c in self.controllers.values():
            c.stop()
        self.informers.stop()
