"""Garbage collector: ownerReference cascade deletion.

Reference: pkg/controller/garbagecollector — builds a cluster-wide
dependency graph from ownerReferences and, when an owner disappears,
deletes its dependents (background policy) unless they carry the orphan
finalizer.  Ours keeps the graph implicit: owner-delete events enqueue a
sweep of that owner's dependents, and a periodic full scan reaps
orphans whose controller owner no longer exists (covering events missed
across restarts — the reference gets the same property from its initial
graph build).

Orphan policy: deleting an owner with
`meta.annotations["kubernetes.io/orphan"] = "true"` skips the cascade
and strips the dependents' ownerReferences instead (the
DeletePropagationOrphan analogue without finalizer machinery).
"""

from __future__ import annotations

import threading
from typing import List

from ..api import store as st
from ..api import types as api
from .base import Controller, obj_key, split_key

# kinds that can OWN dependents (watching these for deletes drives the
# cascade; the orphan scan covers everything else)
OWNER_KINDS = (
    "Deployment", "ReplicaSet", "Job", "StatefulSet", "DaemonSet", "CronJob",
)
# kinds swept for dependents
DEPENDENT_KINDS = ("ReplicaSet", "Job", "Pod")

ORPHAN_ANNOTATION = "kubernetes.io/orphan"


class GarbageCollector(Controller):
    KIND = "GarbageCollection"
    ORPHAN_SCAN_INTERVAL = 5.0

    def register(self) -> None:
        for kind in OWNER_KINDS:
            self.informers.informer(kind).add_handler(self._on_owner)
        self._scan_stop = threading.Event()
        self._scan_thread = threading.Thread(
            target=self._orphan_scan_loop, name="gc-orphan-scan", daemon=True
        )
        self._scan_thread.start()

    def stop(self) -> None:
        if hasattr(self, "_scan_stop"):
            self._scan_stop.set()
        super().stop()

    def _on_owner(self, typ: str, obj, old) -> None:
        if typ == st.DELETED:
            orphan = (
                obj.meta.annotations.get(ORPHAN_ANNOTATION) == "true"
                if hasattr(obj.meta, "annotations")
                else False
            )
            mode = "orphan" if orphan else "delete"
            self.queue.add(
                f"{mode}|{obj.KIND}|{obj.meta.namespace}|{obj.meta.name}"
            )

    def _deps(self, dep_kind: str, namespace=None):
        """Dependent candidates from the INFORMER cache, not store.list:
        the store list deep-copies every object under the store lock, and
        the GC's 5 s cadence over a 5k-node churn cluster turns that into
        a write-path-starving copy storm (the r4 verdict's Weak #6).
        Mutation-bearing paths re-read through the store before writing."""
        return [
            d
            for d in self.informers.informer(dep_kind).list()
            if namespace is None or d.meta.namespace == namespace
        ]

    def sync(self, key: str) -> None:
        mode, kind, namespace, name = key.split("|", 3)
        for dep_kind in DEPENDENT_KINDS:
            for dep in self._deps(dep_kind, namespace):
                refs = [
                    r for r in dep.meta.owner_references
                    if r.kind == kind and r.name == name
                ]
                if not refs:
                    continue
                if mode == "orphan":
                    try:
                        fresh = self.store.get(
                            dep.KIND, dep.meta.name, dep.meta.namespace
                        )
                        fresh.meta.owner_references = [
                            r for r in fresh.meta.owner_references
                            if not (r.kind == kind and r.name == name)
                        ]
                        self.store.update(fresh)
                    except (st.NotFound, st.Conflict):
                        pass
                else:
                    self._delete(dep)

    def _delete(self, obj) -> None:
        try:
            self.store.delete(obj.KIND, obj.meta.name, obj.meta.namespace)
        except KeyError:
            pass  # already gone

    # -- orphan scan (the graph-rebuild half) ------------------------------

    def _orphan_scan_loop(self) -> None:
        while not self._scan_stop.wait(self.ORPHAN_SCAN_INTERVAL):
            try:
                self.scan_orphans()
            except Exception:
                pass

    def scan_orphans(self) -> int:
        """Delete dependents whose CONTROLLER owner no longer exists
        (deletes missed while down; the reference's initial graph sync).
        Returns the number reaped."""
        reaped = 0
        for dep_kind in DEPENDENT_KINDS:
            for dep in self._deps(dep_kind):
                ctrl = next(
                    (r for r in dep.meta.owner_references if r.controller),
                    None,
                )
                if ctrl is None:
                    continue
                owner_cache = self.informers.informer(ctrl.kind)
                if any(
                    o.meta.name == ctrl.name
                    and o.meta.namespace == dep.meta.namespace
                    for o in owner_cache.list()
                ):
                    continue
                # the informer may simply lag the store: confirm against
                # the source of truth before reaping
                try:
                    self.store.get(ctrl.kind, ctrl.name, dep.meta.namespace)
                except KeyError:
                    self._delete(dep)
                    reaped += 1
        return reaped
