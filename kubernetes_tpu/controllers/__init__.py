"""Workload control loops over the informer/workqueue substrate
(reference: pkg/controller, registered via controllermanager.go:515)."""

from .base import Controller
from .deployment import DeploymentController
from .job import JobController
from .manager import ControllerManager
from .nodelifecycle import NodeLifecycleController
from .replicaset import ReplicaSetController

__all__ = [
    "Controller",
    "ControllerManager",
    "DeploymentController",
    "JobController",
    "NodeLifecycleController",
    "ReplicaSetController",
]
