"""Horizontal pod autoscaler.

Reference: pkg/controller/podautoscaler/horizontal.go:125
(reconcileAutoscaler) + replica_calculator.go (GetResourceReplicas):
desired = ceil(current * avgUtilization / target), with a ±10%
tolerance band so tiny drift doesn't flap, clamped to
[minReplicas, maxReplicas], and a downscale stabilization window so a
momentary dip doesn't shrink the fleet (the
--horizontal-pod-autoscaler-downscale-stabilization default is 300 s;
tests tune `downscale_stabilization_s`).

The metrics pipeline is the node agents' PodMetrics objects
(metrics.k8s.io shape) — utilization = usage / request per pod,
averaged over the target's pods that have both.
"""

from __future__ import annotations

import math
import time
from typing import List, Optional

from ..api import store as st
from ..api import types as api
from .base import Controller, split_key

TOLERANCE = 0.1  # horizontal.go tolerance


class HorizontalPodAutoscalerController(Controller):
    KIND = "HorizontalPodAutoscaler"

    # resync cadence: metrics change without object events, so HPAs are
    # re-queued periodically (the reference's 15 s resync)
    RESYNC_S = 1.0

    def __init__(self, *args, downscale_stabilization_s: float = 300.0, **kw):
        super().__init__(*args, **kw)
        self.downscale_stabilization_s = downscale_stabilization_s
        self.clock = time.monotonic
        self._recommendations: dict = {}  # key -> [(t, desired), ...]

    def register(self) -> None:
        self.informers.informer("HorizontalPodAutoscaler").add_handler(
            self._on_hpa
        )
        self.informers.informer("PodMetrics").add_handler(self._on_metrics)

    def _on_hpa(self, typ: str, hpa, old) -> None:
        self.enqueue(hpa)

    def _on_metrics(self, typ: str, m, old) -> None:
        # fresh samples re-evaluate every HPA in that namespace
        for hpa in self.informers.informer("HorizontalPodAutoscaler").list():
            if hpa.meta.namespace == m.meta.namespace:
                self.enqueue(hpa)

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        try:
            hpa = self.store.get("HorizontalPodAutoscaler", name, namespace)
        except st.NotFound:
            self._recommendations.pop(key, None)
            return
        ref = hpa.spec.scale_target_ref
        try:
            target = self.store.get(ref.kind, ref.name, namespace)
        except st.NotFound:
            return
        current = target.spec.replicas
        pods = self._target_pods(namespace, target)
        utilization, desired = self._desired_replicas(hpa, current, pods)
        desired = max(hpa.spec.min_replicas, min(hpa.spec.max_replicas, desired))

        # downscale stabilization: recommend the MAX over the window
        now = self.clock()
        recs = self._recommendations.setdefault(key, [])
        recs.append((now, desired))
        cutoff = now - self.downscale_stabilization_s
        recs[:] = [(t, d) for t, d in recs if t >= cutoff]
        if desired < current:
            desired = max(d for _, d in recs)
        if desired != current:
            target.spec.replicas = desired
            self.store.update(target, force=True)
            # wall clock, like every other persisted timestamp: the
            # monotonic value used for stabilization bookkeeping is
            # meaningless to API consumers and across restarts
            hpa.status.last_scale_time = time.time()
        hpa.status.current_replicas = current
        hpa.status.desired_replicas = desired
        hpa.status.current_cpu_utilization_percentage = (
            int(utilization) if utilization is not None else None
        )
        self.store.update(hpa, force=True)

    # -- metrics math (replica_calculator.go) --------------------------------

    def _target_pods(self, namespace: str, target) -> List[api.Pod]:
        # ALL active pods, not just Running: a just-created Pending pod
        # must participate as a missing-metrics pod (conservatively 0%
        # on scale-up) or the calculator compounds fresh scale-ups into
        # overshoot (replica_calculator.go's ignored-pods set)
        sel = target.spec.selector
        return [
            p
            for p in self.informers.informer("Pod").list()
            if p.meta.namespace == namespace
            and p.status.phase not in ("Succeeded", "Failed")
            and (sel is None or sel.matches(p.meta.labels))
        ]

    def _desired_replicas(self, hpa, current: int, pods: List[api.Pod]):
        """(utilization%, desired) — GetResourceReplicas: sum-based
        utilization, and pods MISSING metrics are assumed conservative
        (0% when scaling up, 100% when scaling down) so a fresh scale-up
        whose new pods haven't reported yet doesn't compound into an
        overshoot."""
        target_pct = hpa.spec.target_cpu_utilization_percentage
        usages, reqs, missing_req, missing_count = [], [], 0, 0
        for p in pods:
            req = p.resource_requests().get(api.CPU, 0)
            if not req:
                continue
            usage = None
            if p.status.phase == "Running":
                try:
                    m = self.store.get(
                        "PodMetrics", p.meta.name, p.meta.namespace
                    )
                    usage = m.usage.get(api.CPU)
                except st.NotFound:
                    usage = None
            if usage is None:
                missing_req += req  # unstarted or unreported
                missing_count += 1
            else:
                usages.append(usage)
                reqs.append(req)
        if not reqs:
            return None, current
        # sum-based utilization over the pods that reported; the desired
        # count scales the READY pod count, not spec.replicas — a scale-up
        # the informers haven't materialized yet must not compound
        # (replica_calculator.go GetResourceReplicas)
        ready = len(reqs)
        utilization = 100.0 * sum(usages) / sum(reqs)
        ratio = utilization / target_pct
        if not missing_req:
            if abs(ratio - 1.0) <= TOLERANCE:
                return utilization, current
            return utilization, math.ceil(ready * ratio)
        if ratio > 1.0:
            # rebalance with missing pods at 0 usage
            new_ratio = (
                100.0 * sum(usages) / (sum(reqs) + missing_req)
            ) / target_pct
            if new_ratio <= 1.0 + TOLERANCE:
                return utilization, current
        elif ratio < 1.0:
            # rebalance with missing pods at full usage
            new_ratio = (
                100.0
                * (sum(usages) + missing_req)
                / (sum(reqs) + missing_req)
            ) / target_pct
            if new_ratio >= 1.0 - TOLERANCE:
                return utilization, current
        else:
            return utilization, current
        return utilization, math.ceil(new_ratio * (ready + missing_count))
