"""The controller worker pattern: shared informers feed a rate-limited
workqueue; N worker threads pop keys and reconcile desired vs actual
through the store.

Reference: every controller in pkg/controller follows this shape —
registered at cmd/kube-controller-manager/app/controllermanager.go:515,
run as Run(workers) with queue.Get → syncHandler(key) → lister-read →
clientset writes → watch events re-enqueue (level-triggered).
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Tuple

from ..api import store as st
from ..api import types as api
from ..client.informers import InformerFactory
from ..client.workqueue import WorkQueue

logger = logging.getLogger(__name__)


def obj_key(obj) -> str:
    return f"{obj.meta.namespace}/{obj.meta.name}"


def split_key(key: str) -> Tuple[str, str]:
    namespace, _, name = key.partition("/")
    return namespace, name


def controller_owner(obj) -> Optional[api.OwnerReference]:
    """The managing controller's OwnerReference, if any
    (metav1.GetControllerOf)."""
    for ref in obj.meta.owner_references:
        if ref.controller:
            return ref
    return None


class Expectations:
    """ControllerExpectations (pkg/controller/controller_utils.go): after
    a sync issues creates/deletes, the controller must not act on that
    key again until the informer has OBSERVED them — the informer cache
    lags the store, and recounting it early double-provisions (fresh
    names defeat AlreadyExists)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._adds: dict = {}
        self._dels: dict = {}

    def expect_creations(self, key: str, n: int) -> None:
        with self._lock:
            self._adds[key] = self._adds.get(key, 0) + n

    def expect_deletions(self, key: str, n: int) -> None:
        with self._lock:
            self._dels[key] = self._dels.get(key, 0) + n

    def creation_observed(self, key: str) -> None:
        with self._lock:
            if self._adds.get(key, 0) > 0:
                self._adds[key] -= 1

    def deletion_observed(self, key: str) -> None:
        with self._lock:
            if self._dels.get(key, 0) > 0:
                self._dels[key] -= 1

    def satisfied(self, key: str) -> bool:
        with self._lock:
            return self._adds.get(key, 0) <= 0 and self._dels.get(key, 0) <= 0

    def forget(self, key: str) -> None:
        with self._lock:
            self._adds.pop(key, None)
            self._dels.pop(key, None)


class Controller:
    """Base: owns a workqueue + workers; subclasses set KIND, wire
    informer handlers in `register()`, and implement `sync(key)`.

    sync() must be level-based and idempotent: it reads the CURRENT
    state and converges one step; errors requeue the key with
    rate-limited backoff (workqueue.add_rate_limited)."""

    KIND = ""

    def __init__(
        self,
        store: st.Store,
        informers: InformerFactory,
        workers: int = 2,
    ):
        self.store = store
        self.informers = informers
        self.queue = WorkQueue()
        self.expectations = Expectations()
        self.workers = workers
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

    # -- wiring ------------------------------------------------------------

    def register(self) -> None:
        """Subclasses add informer handlers here (called by start)."""
        raise NotImplementedError

    def enqueue(self, obj) -> None:
        self.queue.add(obj_key(obj))

    def enqueue_owner(self, pod: api.Pod, kind: Optional[str] = None) -> None:
        """Route a dependent-object event to its controller's key
        (resolveControllerRef in every reference controller)."""
        ref = controller_owner(pod)
        if ref is not None and ref.kind == (kind or self.KIND):
            self.queue.add(f"{pod.meta.namespace}/{ref.name}")

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.register()
        for i in range(self.workers):
            t = threading.Thread(
                target=self._worker,
                name=f"{self.KIND.lower()}-worker-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shutdown()
        for t in self._threads:
            t.join(timeout=5)

    def _worker(self) -> None:
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.2)
            if key is None:
                continue
            try:
                self.sync(key)
            except st.Conflict:
                # optimistic-concurrency race: retry against fresh state
                self.queue.done(key)
                self.queue.add_rate_limited(key)
                continue
            except Exception:
                logger.exception("%s: sync(%s) failed", self.KIND, key)
                self.queue.done(key)
                self.queue.add_rate_limited(key)
                continue
            self.queue.done(key)
            self.queue.forget(key)

    # -- reconcile ---------------------------------------------------------

    def sync(self, key: str) -> None:
        raise NotImplementedError

    # -- shared helpers ----------------------------------------------------

    def pods_owned_by(
        self, namespace: str, owner_kind: str, owner_name: str
    ) -> List[api.Pod]:
        pods = self.informers.informer("Pod").list()
        out = []
        for p in pods:
            if p.meta.namespace != namespace:
                continue
            ref = controller_owner(p)
            if ref is not None and ref.kind == owner_kind and ref.name == owner_name:
                out.append(p)
        return out
