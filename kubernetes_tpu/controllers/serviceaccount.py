"""ServiceAccount + TTL-after-finished controllers.

Reference: pkg/controller/serviceaccount (ensures every namespace has a
"default" ServiceAccount; pods are defaulted to it at admission —
plugin/pkg/admission/serviceaccount) and pkg/controller/ttlafterfinished
(deletes finished Jobs after spec.ttlSecondsAfterFinished; their pods
follow via the GC's ownerReference cascade).
"""

from __future__ import annotations

import threading
import time
from typing import Any

from ..api import admission as adm
from ..api import store as st
from ..api import types as api
from .base import Controller, split_key


def default_service_account(obj: Any, operation: str) -> None:
    """Admission defaulter: every pod runs as a ServiceAccount."""
    if isinstance(obj, api.Pod) and not obj.spec.service_account:
        obj.spec.service_account = "default"


class ServiceAccountController(Controller):
    KIND = "ServiceAccount"

    def register(self) -> None:
        self.informers.informer("Namespace").add_handler(self._on_namespace)
        self.informers.informer("ServiceAccount").add_handler(self._on_sa)

    def _on_namespace(self, typ: str, ns, old) -> None:
        if typ != st.DELETED:
            self.queue.add(f"{ns.meta.name}/default")

    def _on_sa(self, typ: str, sa, old) -> None:
        if typ == st.DELETED:
            # recreate the default account if it goes missing
            self.enqueue(sa)

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        if name != "default":
            return
        try:
            ns = self.store.get("Namespace", namespace, "")
        except st.NotFound:
            return
        if ns.status.phase == "Terminating":
            return
        try:
            self.store.get("ServiceAccount", "default", namespace)
        except st.NotFound:
            try:
                self.store.create(
                    api.ServiceAccount(
                        meta=api.ObjectMeta(
                            name="default", namespace=namespace
                        )
                    )
                )
            except st.AlreadyExists:
                pass


class TTLAfterFinishedController(Controller):
    """Deletes Jobs spec.ttl_seconds_after_finished seconds after they
    complete (ttlafterfinished/ttlafterfinished_controller.go); a timer
    re-queues jobs whose TTL hasn't expired yet."""

    KIND = "Job"
    NAME = "TTLAfterFinished"  # manager key (JobController owns "Job")

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.clock = time.time
        self._timers: list = []

    def register(self) -> None:
        self.informers.informer("Job").add_handler(self._on_job)

    def _on_job(self, typ: str, job, old) -> None:
        if typ != st.DELETED:
            self.enqueue(job)

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        try:
            job = self.store.get("Job", name, namespace)
        except st.NotFound:
            return
        ttl = job.spec.ttl_seconds_after_finished
        if ttl is None:
            return
        # the job controller stamps completion_time for success AND
        # backoff-limit failure — that's the finished signal
        if job.status.completion_time is None:
            return
        remaining = job.status.completion_time + ttl - self.clock()
        if remaining <= 0:
            try:
                self.store.delete("Job", name, namespace)
            except st.NotFound:
                pass
            return
        t = threading.Timer(remaining, lambda: self.queue.add(key))
        t.daemon = True
        t.start()
        self._timers.append(t)

    def stop(self) -> None:
        for t in self._timers:
            t.cancel()
        super().stop()
