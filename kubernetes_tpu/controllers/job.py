"""Job controller: run template pods to `completions` with at most
`parallelism` active.

Reference: pkg/controller/job/job_controller.go syncJob — active =
non-terminal owned pods, succeeded counts Succeeded phases, new pods
created while active < parallelism and succeeded + active < completions;
job completes when succeeded >= completions.  Pod phases are written by
the node agent in the reference; tests (and the hollow-node sim) flip
them through the store.
"""

from __future__ import annotations

import itertools
import time

from ..api import store as st
from ..api import types as api
from .base import Controller, split_key
from .replicaset import pod_from_template

_suffix = itertools.count(1)


class JobController(Controller):
    KIND = "Job"

    def register(self) -> None:
        self.informers.informer("Job").add_handler(self._on_job)
        self.informers.informer("Pod").add_handler(self._on_pod)

    def _on_job(self, typ: str, job, old) -> None:
        # DELETED included: sync's NotFound path cascade-deletes owned pods
        self.enqueue(job)

    def _on_pod(self, typ: str, pod: api.Pod, old) -> None:
        ref = None
        for r in pod.meta.owner_references:
            if r.controller and r.kind == self.KIND:
                ref = r
        if ref is not None:
            key = f"{pod.meta.namespace}/{ref.name}"
            if typ == st.ADDED:
                self.expectations.creation_observed(key)
            elif typ == st.DELETED:
                self.expectations.deletion_observed(key)
            self.queue.add(key)

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        try:
            job = self.store.get("Job", name, namespace)
        except st.NotFound:
            self.expectations.forget(key)
            for pod in self.pods_owned_by(namespace, "Job", name):
                try:
                    self.store.delete("Pod", pod.meta.name, namespace)
                except st.NotFound:
                    pass
            return
        owned = self.pods_owned_by(namespace, "Job", name)
        succeeded = sum(1 for p in owned if p.status.phase == "Succeeded")
        failed = sum(1 for p in owned if p.status.phase == "Failed")
        active = [
            p for p in owned if p.status.phase not in ("Succeeded", "Failed")
        ]
        completions = (
            job.spec.completions
            if job.spec.completions is not None
            else job.spec.parallelism
        )
        # terminal either way: success (completions reached) OR failure
        # (backoffLimit exceeded — the job_controller.go Failed
        # condition); a failed job must still record completion_time so
        # consumers (CronJob's Forbid policy) see it as finished
        done = succeeded >= completions or failed > job.spec.backoff_limit
        if (
            not done
            and failed <= job.spec.backoff_limit
            and self.expectations.satisfied(key)
        ):
            want_new = min(
                job.spec.parallelism - len(active),
                completions - succeeded - len(active),
            )
            if want_new > 0:
                self.expectations.expect_creations(key, want_new)
            for _ in range(max(0, want_new)):
                pod = pod_from_template(
                    job.spec.template, job, f"{name}-{next(_suffix):05d}"
                )
                try:
                    self.store.create(pod)
                except st.AlreadyExists:
                    self.expectations.creation_observed(key)
                    self.queue.add(key)
        # write status ONLY on change — an unconditional update MODIFIED-
        # events this key back into a permanent reconcile loop
        if (
            job.status.active != len(active)
            or job.status.succeeded != succeeded
            or job.status.failed != failed
            or (done and job.status.completion_time is None)
        ):
            job.status.active = len(active)
            job.status.succeeded = succeeded
            job.status.failed = failed
            if done and job.status.completion_time is None:
                job.status.completion_time = time.time()
            self.store.update(job)
