"""DaemonSet controller: one pod per eligible node.

Reference: pkg/controller/daemon/daemon_controller.go — for every node
passing the template's node selector and tolerating the node's
NoSchedule taints, ensure exactly one daemon pod; nodes joining get a
pod, nodes leaving lose theirs via the GC cascade.  Like the modern
reference (post-1.12), daemon pods route THROUGH the default scheduler:
the controller stamps a per-node required nodeAffinity on
kubernetes.io/hostname (replaceDaemonSetPodNodeNameNodeAffinity,
pkg/controller/daemon/util/daemonset_util.go) plus the implicit daemon
tolerations (unschedulable/not-ready/unreachable), and the scheduler's
fit/ports/volume kernels decide — a FULL node rejects its daemon pod
with a FailedScheduling event instead of silently overcommitting."""

from __future__ import annotations

from ..api import store as st
from ..api import types as api
from .base import Controller, split_key


class DaemonSetController(Controller):
    KIND = "DaemonSet"

    def register(self) -> None:
        self.informers.informer("DaemonSet").add_handler(self._on_ds)
        self.informers.informer("Pod").add_handler(self._on_pod)
        self.informers.informer("Node").add_handler(self._on_node)

    def _on_ds(self, typ: str, obj, old) -> None:
        if typ != st.DELETED:
            self.enqueue(obj)

    def _on_pod(self, typ: str, pod, old) -> None:
        self.enqueue_owner(pod, "DaemonSet")

    def _on_node(self, typ: str, node, old) -> None:
        # only eligibility-relevant changes fan out — heartbeat status
        # updates would otherwise enqueue every DaemonSet per node per
        # interval (O(nodes x daemonsets) steady-state churn)
        if typ == st.MODIFIED and old is not None:
            if (
                old.meta.labels == node.meta.labels
                and old.spec.taints == node.spec.taints
                and old.spec.unschedulable == node.spec.unschedulable
            ):
                return
        for ds in self.informers.informer("DaemonSet").list():
            self.enqueue(ds)

    def _eligible(self, ds: api.DaemonSet, node: api.Node) -> bool:
        tmpl = ds.spec.template.spec
        for k, v in tmpl.node_selector.items():
            if node.meta.labels.get(k) != v:
                return False
        for taint in node.effective_taints():
            if taint.effect != api.NO_SCHEDULE:
                continue
            # daemon pods implicitly tolerate cordoning — the controller
            # adds node.kubernetes.io/unschedulable automatically
            # (daemon_controller.go AddOrUpdateDaemonPodTolerations), so
            # cordon must not evict running agents
            if taint.key == api.TAINT_NODE_UNSCHEDULABLE:
                continue
            if not any(
                self._tolerates(t, taint) for t in tmpl.tolerations
            ):
                return False
        return True

    @staticmethod
    def _tolerates(tol: api.Toleration, taint: api.Taint) -> bool:
        """Toleration-vs-taint match incl. the EFFECT dimension (a
        NoExecute-only toleration must not cover a NoSchedule taint).
        An empty key with operator Exists tolerates EVERYTHING (the
        node-agent tolerate-all pattern, core/v1 Toleration docs)."""
        if tol.effect and tol.effect != taint.effect:
            return False
        if tol.op == api.OP_EXISTS and not tol.key:
            return True
        if tol.key != taint.key:
            return False
        if tol.op == api.OP_EXISTS:
            return True
        return tol.value == taint.value

    @staticmethod
    def _pinned_node(pod: api.Pod) -> str:
        aff = pod.spec.affinity
        na = aff.node_affinity if aff else None
        if na is None or na.required is None:
            return ""
        for term in na.required.terms:
            for req in term.match_expressions:
                if req.key == api.LABEL_HOSTNAME and req.op == api.OP_IN:
                    return req.values[0] if req.values else ""
        return ""

    @staticmethod
    def _pin_to_node(pod: api.Pod, node_name: str) -> None:
        """Per-node pin via required nodeAffinity on the hostname label
        (daemonset_util.go ReplaceDaemonSetPodNodeNameNodeAffinity) plus
        the implicit daemon tolerations
        (AddOrUpdateDaemonPodTolerations): daemon pods survive cordons
        and node-pressure taints but still face resource/port fit."""
        pin = api.NodeSelector(terms=[
            api.NodeSelectorTerm(match_expressions=[
                api.Requirement(api.LABEL_HOSTNAME, api.OP_IN, [node_name])
            ])
        ])
        aff = pod.spec.affinity or api.Affinity()
        na = aff.node_affinity or api.NodeAffinity()
        na.required = pin  # replace: the per-node pin owns placement
        aff.node_affinity = na
        pod.spec.affinity = aff
        for key_, effect in (
            (api.TAINT_NODE_UNSCHEDULABLE, api.NO_SCHEDULE),
            (api.TAINT_NODE_NOT_READY, api.NO_EXECUTE),
            (api.TAINT_NODE_UNREACHABLE, api.NO_EXECUTE),
        ):
            tol = api.Toleration(key=key_, op=api.OP_EXISTS, effect=effect)
            if tol not in pod.spec.tolerations:
                pod.spec.tolerations.append(tol)

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        try:
            ds = self.store.get("DaemonSet", name, namespace)
        except st.NotFound:
            return  # GC cascades the pods
        nodes = self.informers.informer("Node").list()
        eligible = {n.meta.name for n in nodes if self._eligible(ds, n)}
        pods = self.pods_owned_by(namespace, "DaemonSet", name)
        by_node = {}
        for p in pods:
            # a daemon pod belongs to its PIN target even before the
            # scheduler binds it — keying pending pods on "" would make
            # the next sync double-create and reap them
            node = p.spec.node_name or self._pinned_node(p) or ""
            by_node.setdefault(node, []).append(p)

        # delete pods on ineligible/vanished nodes + duplicates
        for node_name, plist in by_node.items():
            doomed = plist[1:] if node_name in eligible else plist
            for p in doomed:
                try:
                    self.store.delete("Pod", p.meta.name, namespace)
                except st.NotFound:
                    pass
        # create missing daemon pods — scheduled by the default
        # scheduler via a per-node hostname affinity, so they pass the
        # fit/ports/volume kernels like any other pod
        for node_name in sorted(eligible - set(by_node)):
            template = api.clone(ds.spec.template)
            pod = api.Pod(
                meta=api.ObjectMeta(
                    name=f"{name}-{node_name}",
                    namespace=namespace,
                    labels=dict(template.meta.labels),
                    owner_references=[
                        api.OwnerReference(
                            kind="DaemonSet", name=name,
                            uid=ds.meta.uid, controller=True,
                        )
                    ],
                ),
                spec=api.clone(template.spec),
            )
            self._pin_to_node(pod, node_name)
            try:
                self.store.create(pod)
            except st.AlreadyExists:
                pass
        self._write_status(ds, namespace, name, len(eligible))

    def _write_status(self, ds, namespace, name, desired) -> None:
        pods = self.pods_owned_by(namespace, "DaemonSet", name)
        current = len(pods)
        ready = sum(1 for p in pods if p.status.phase == "Running")
        if (
            ds.status.desired_number_scheduled == desired
            and ds.status.current_number_scheduled == current
            and ds.status.number_ready == ready
        ):
            return
        try:
            fresh = self.store.get("DaemonSet", name, namespace)
        except st.NotFound:
            return
        fresh.status.desired_number_scheduled = desired
        fresh.status.current_number_scheduled = current
        fresh.status.number_ready = ready
        self.store.update(fresh)
