"""Deployment controller: manage ReplicaSets per template revision.

Reference: pkg/controller/deployment/deployment_controller.go +
sync.go/rolling.go/recreate.go.  Revision identity is a stable hash of
the pod template (the pod-template-hash label pattern).  RollingUpdate
steps the new revision up and old ones down under maxSurge /
maxUnavailable (absolute counts; the availability floor is
desired - maxUnavailable, the capacity ceiling desired + maxSurge),
advancing as RS status events report pods ready; Recreate drains old
revisions fully before scaling the new one.
"""

from __future__ import annotations

import hashlib

from ..api import store as st
from ..api import types as api
from .base import Controller, controller_owner, split_key


def template_hash(template: api.PodTemplateSpec) -> str:
    """Stable content hash of a pod template (pod-template-hash)."""
    import dataclasses
    import json

    def enc(o):
        if dataclasses.is_dataclass(o) and not isinstance(o, type):
            return {
                f.name: enc(getattr(o, f.name))
                for f in dataclasses.fields(o)
            }
        if isinstance(o, dict):
            return {k: enc(v) for k, v in sorted(o.items())}
        if isinstance(o, list):
            return [enc(v) for v in o]
        return o

    doc = json.dumps(enc(template), sort_keys=True, default=str)
    return hashlib.sha1(doc.encode()).hexdigest()[:10]


class DeploymentController(Controller):
    KIND = "Deployment"

    def register(self) -> None:
        self.informers.informer("Deployment").add_handler(self._on_dep)
        self.informers.informer("ReplicaSet").add_handler(self._on_rs)

    def _on_dep(self, typ: str, dep, old) -> None:
        self.enqueue(dep)

    def _on_rs(self, typ: str, rs, old) -> None:
        ref = controller_owner(rs)
        if ref is not None and ref.kind == "Deployment":
            self.queue.add(f"{rs.meta.namespace}/{ref.name}")

    def _owned_rs(self, namespace: str, name: str):
        out = []
        for rs in self.informers.informer("ReplicaSet").list():
            if rs.meta.namespace != namespace:
                continue
            ref = controller_owner(rs)
            if ref is not None and ref.kind == "Deployment" and ref.name == name:
                out.append(rs)
        return out

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        try:
            dep = self.store.get("Deployment", name, namespace)
        except st.NotFound:
            # Deployment deleted: the garbage collector cascades to owned
            # ReplicaSets via ownerReferences — deleting here too would
            # bypass the orphan annotation
            return
        rev = template_hash(dep.spec.template)
        rs_name = f"{name}-{rev}"
        owned = self._owned_rs(namespace, name)
        current = next((r for r in owned if r.meta.name == rs_name), None)
        old_active = [
            r for r in owned
            if r.meta.name != rs_name and r.spec.replicas > 0
        ]
        strategy = dep.spec.strategy
        surge, unavail = self._bounds(strategy)
        if current is None:
            # Initial replica count honours the rollout bounds: a fresh
            # deployment (no old revisions) starts at full scale; a
            # template change starts the new RS within maxSurge
            # (rolling.go NewRSNewReplicas) or at 0 for Recreate.
            if not old_active:
                initial = dep.spec.replicas
            elif strategy.type == "Recreate":
                initial = 0
            else:
                total = sum(r.spec.replicas for r in old_active)
                initial = max(
                    0, min(dep.spec.replicas,
                           dep.spec.replicas + surge - total)
                )
            template = api.clone(dep.spec.template)
            template.meta.labels.setdefault("pod-template-hash", rev)
            rs = api.ReplicaSet(
                meta=api.ObjectMeta(
                    name=rs_name,
                    namespace=namespace,
                    labels=dict(template.meta.labels),
                    owner_references=[
                        api.OwnerReference(
                            kind="Deployment",
                            name=name,
                            uid=dep.meta.uid,
                            controller=True,
                        )
                    ],
                ),
                spec=api.ReplicaSetSpec(
                    replicas=initial,
                    selector=api.LabelSelector(
                        match_labels=dict(template.meta.labels)
                    ),
                    template=template,
                ),
            )
            try:
                self.store.create(rs)
            except st.AlreadyExists:
                self.queue.add(key)
                return
        elif not old_active:
            # steady state / plain scaling: no rollout in progress
            if current.spec.replicas != dep.spec.replicas:
                fresh = self.store.get("ReplicaSet", rs_name, namespace)
                fresh.spec.replicas = dep.spec.replicas
                self.store.update(fresh)
        elif strategy.type == "Recreate":
            # drain old revisions fully, then bring the new one up
            # (pkg/controller/deployment/recreate.go)
            for rs in old_active:
                fresh = self.store.get("ReplicaSet", rs.meta.name, namespace)
                fresh.spec.replicas = 0
                self.store.update(fresh)
            drained = all(
                r.status.replicas == 0
                for r in owned
                if r.meta.name != rs_name
            )
            if drained and current.spec.replicas != dep.spec.replicas:
                fresh = self.store.get("ReplicaSet", rs_name, namespace)
                fresh.spec.replicas = dep.spec.replicas
                self.store.update(fresh)
        else:
            self._rolling_step(
                dep, namespace, current, old_active, surge, unavail
            )
        self._write_status(dep, namespace, name, rs_name)

    @staticmethod
    def _bounds(strategy: api.DeploymentStrategy):
        surge = max(0, int(strategy.max_surge))
        unavail = max(0, int(strategy.max_unavailable))
        if surge == 0 and unavail == 0:
            unavail = 1  # validation rejects 0/0; make progress possible
        return surge, unavail

    def _rolling_step(
        self, dep, namespace, current, old_active, surge, unavail
    ) -> None:
        """One bounded rollout step (rolling.go reconcileNewReplicaSet /
        reconcileOldReplicaSets): scale the new RS up to
        desired+maxSurge minus what exists, scale old RSes down by the
        ready headroom above desired-maxUnavailable.  RS status events
        re-enqueue the deployment, so the rollout advances as pods come
        up — availability never drops below desired - maxUnavailable and
        total never exceeds desired + maxSurge."""
        desired = dep.spec.replicas
        all_rs = [current] + old_active
        total = sum(r.spec.replicas for r in all_rs)
        # scale up new within surge budget
        if current.spec.replicas < desired:
            allowed = desired + surge - total
            if allowed > 0:
                fresh = self.store.get(
                    "ReplicaSet", current.meta.name, namespace
                )
                fresh.spec.replicas = min(
                    desired, current.spec.replicas + allowed
                )
                self.store.update(fresh)
                return  # re-enqueued by the RS event; one step at a time
        # scale down old within the availability budget
        ready_total = sum(r.status.ready_replicas for r in all_rs)
        min_available = desired - unavail
        can_remove = ready_total - min_available
        for rs in sorted(old_active, key=lambda r: r.meta.name):
            if can_remove <= 0:
                break
            step = min(rs.spec.replicas, can_remove)
            if step <= 0:
                continue
            fresh = self.store.get("ReplicaSet", rs.meta.name, namespace)
            fresh.spec.replicas = max(0, fresh.spec.replicas - step)
            self.store.update(fresh)
            can_remove -= step

    def _write_status(self, dep, namespace, name, rs_name) -> None:
        # status from owned RS; write ONLY on change — an unconditional
        # update MODIFIED-events this key back into a permanent loop
        owned = self._owned_rs(namespace, name)
        replicas = sum(r.status.replicas for r in owned)
        updated = sum(
            r.status.replicas for r in owned if r.meta.name == rs_name
        )
        ready = sum(r.status.ready_replicas for r in owned)
        if (
            dep.status.replicas != replicas
            or dep.status.updated_replicas != updated
            or dep.status.ready_replicas != ready
            or dep.status.observed_generation != dep.meta.generation
        ):
            dep_fresh = self.store.get("Deployment", name, namespace)
            dep_fresh.status.replicas = replicas
            dep_fresh.status.updated_replicas = updated
            dep_fresh.status.ready_replicas = ready
            dep_fresh.status.observed_generation = dep_fresh.meta.generation
            self.store.update(dep_fresh)
