"""Deployment controller: manage ReplicaSets per template revision.

Reference: pkg/controller/deployment/deployment_controller.go +
sync.go/rolling.go.  Revision identity is a stable hash of the pod
template (the pod-template-hash label pattern); rollout is simplified to
whole-RS transitions — the new revision's RS scales to spec.replicas and
every old RS scales to 0 in one reconcile (maxSurge/maxUnavailable
stepping is a documented divergence; capacity-safe stepping matters on
real kubelets, not against the in-memory store).
"""

from __future__ import annotations

import hashlib

from ..api import store as st
from ..api import types as api
from .base import Controller, controller_owner, split_key


def template_hash(template: api.PodTemplateSpec) -> str:
    """Stable content hash of a pod template (pod-template-hash)."""
    import dataclasses
    import json

    def enc(o):
        if dataclasses.is_dataclass(o) and not isinstance(o, type):
            return {
                f.name: enc(getattr(o, f.name))
                for f in dataclasses.fields(o)
            }
        if isinstance(o, dict):
            return {k: enc(v) for k, v in sorted(o.items())}
        if isinstance(o, list):
            return [enc(v) for v in o]
        return o

    doc = json.dumps(enc(template), sort_keys=True, default=str)
    return hashlib.sha1(doc.encode()).hexdigest()[:10]


class DeploymentController(Controller):
    KIND = "Deployment"

    def register(self) -> None:
        self.informers.informer("Deployment").add_handler(self._on_dep)
        self.informers.informer("ReplicaSet").add_handler(self._on_rs)

    def _on_dep(self, typ: str, dep, old) -> None:
        self.enqueue(dep)

    def _on_rs(self, typ: str, rs, old) -> None:
        ref = controller_owner(rs)
        if ref is not None and ref.kind == "Deployment":
            self.queue.add(f"{rs.meta.namespace}/{ref.name}")

    def _owned_rs(self, namespace: str, name: str):
        out = []
        for rs in self.informers.informer("ReplicaSet").list():
            if rs.meta.namespace != namespace:
                continue
            ref = controller_owner(rs)
            if ref is not None and ref.kind == "Deployment" and ref.name == name:
                out.append(rs)
        return out

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        try:
            dep = self.store.get("Deployment", name, namespace)
        except st.NotFound:
            for rs in self._owned_rs(namespace, name):
                try:
                    self.store.delete("ReplicaSet", rs.meta.name, namespace)
                except st.NotFound:
                    pass
            return
        rev = template_hash(dep.spec.template)
        rs_name = f"{name}-{rev}"
        owned = self._owned_rs(namespace, name)
        current = next((r for r in owned if r.meta.name == rs_name), None)
        if current is None:
            template = api.clone(dep.spec.template)
            template.meta.labels.setdefault("pod-template-hash", rev)
            rs = api.ReplicaSet(
                meta=api.ObjectMeta(
                    name=rs_name,
                    namespace=namespace,
                    labels=dict(template.meta.labels),
                    owner_references=[
                        api.OwnerReference(
                            kind="Deployment",
                            name=name,
                            uid=dep.meta.uid,
                            controller=True,
                        )
                    ],
                ),
                spec=api.ReplicaSetSpec(
                    replicas=dep.spec.replicas,
                    selector=api.LabelSelector(
                        match_labels=dict(template.meta.labels)
                    ),
                    template=template,
                ),
            )
            try:
                self.store.create(rs)
            except st.AlreadyExists:
                self.queue.add(key)
                return
        elif current.spec.replicas != dep.spec.replicas:
            fresh = self.store.get("ReplicaSet", rs_name, namespace)
            fresh.spec.replicas = dep.spec.replicas
            self.store.update(fresh)
        # scale old revisions down
        for rs in owned:
            if rs.meta.name != rs_name and rs.spec.replicas != 0:
                fresh = self.store.get("ReplicaSet", rs.meta.name, namespace)
                fresh.spec.replicas = 0
                self.store.update(fresh)
        # status from owned RS; write ONLY on change — an unconditional
        # update MODIFIED-events this key back into a permanent loop
        owned = self._owned_rs(namespace, name)
        replicas = sum(r.status.replicas for r in owned)
        updated = sum(
            r.status.replicas for r in owned if r.meta.name == rs_name
        )
        ready = sum(r.status.ready_replicas for r in owned)
        if (
            dep.status.replicas != replicas
            or dep.status.updated_replicas != updated
            or dep.status.ready_replicas != ready
            or dep.status.observed_generation != dep.meta.generation
        ):
            dep_fresh = self.store.get("Deployment", name, namespace)
            dep_fresh.status.replicas = replicas
            dep_fresh.status.updated_replicas = updated
            dep_fresh.status.ready_replicas = ready
            dep_fresh.status.observed_generation = dep_fresh.meta.generation
            self.store.update(dep_fresh)
