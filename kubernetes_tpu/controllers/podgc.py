"""Pod garbage collector.

Reference: pkg/controller/podgc/gc_controller.go — reaps (1) terminated
pods beyond a threshold (oldest first, so Failed/Succeeded history
stays bounded while recent forensics survive), and (2) pods bound to
nodes that no longer exist (the orphaned-pod sweep).  With the node
agent producing Failed pods on eviction and Jobs producing Succeeded
ones, something must bound that population — exactly why the reference
runs this controller.
"""

from __future__ import annotations

import threading

from ..api import store as st
from ..api import types as api
from .base import Controller

_SYNC_KEY = "podgc"


class PodGCController(Controller):
    KIND = "Pod"
    NAME = "PodGC"
    RESYNC_S = 5.0
    # --terminated-pod-gc-threshold (the reference default is 12500;
    # scaled to the in-process store's population)
    TERMINATED_THRESHOLD = 500

    def register(self) -> None:
        self.informers.informer("Pod").add_handler(self._on_event)
        self.informers.informer("Node").add_handler(self._on_event)
        self._tick_stop = threading.Event()
        self._ticker = threading.Thread(
            target=self._tick, name="podgc-ticker", daemon=True
        )
        self._ticker.start()

    def stop(self) -> None:
        if hasattr(self, "_tick_stop"):
            self._tick_stop.set()
        super().stop()

    def _tick(self) -> None:
        while not self._tick_stop.wait(self.RESYNC_S):
            self.queue.add(_SYNC_KEY)

    def _on_event(self, typ: str, obj, old) -> None:
        if typ == st.DELETED and getattr(obj, "KIND", "") == "Node":
            self.queue.add(_SYNC_KEY)  # orphans appeared

    def sync(self, key: str) -> None:
        pods = self.informers.informer("Pod").list()
        nodes = {n.meta.name for n in self.informers.informer("Node").list()}
        reaped = set()
        # orphaned: bound to a node that no longer exists — confirmed
        # against the STORE first, because the per-kind informer threads
        # are not mutually consistent and a just-created node may not
        # have reached the Node cache yet (the reference double-checks
        # with a live GET for exactly this race, gc_controller.go)
        for p in pods:
            if p.spec.node_name and p.spec.node_name not in nodes:
                try:
                    self.store.get("Node", p.spec.node_name, "")
                    continue  # informer lag; the node exists
                except KeyError:
                    pass
                self._delete(p)
                reaped.add(f"{p.meta.namespace}/{p.meta.name}")
        terminated = sorted(
            (
                p for p in pods
                if p.status.phase in ("Succeeded", "Failed")
                and f"{p.meta.namespace}/{p.meta.name}" not in reaped
            ),
            key=lambda p: p.meta.creation_timestamp or 0.0,
        )
        excess = len(terminated) - self.TERMINATED_THRESHOLD
        for p in terminated[: max(excess, 0)]:
            self._delete(p)

    def _delete(self, pod: api.Pod) -> None:
        try:
            self.store.delete("Pod", pod.meta.name, pod.meta.namespace)
        except KeyError:
            pass
