"""Node lifecycle: heartbeat monitoring → NoExecute taint → eviction.

Reference: pkg/controller/nodelifecycle/node_lifecycle_controller.go:668
monitorNodeHealth marks nodes NotReady when their heartbeat goes stale,
taints them node.kubernetes.io/unreachable:NoExecute, and the taint
eviction controller (pkg/controller/tainteviction) deletes their pods so
they requeue and reschedule elsewhere.

Ours folds both loops into one controller: heartbeats are OBSERVED from
Node write events (any update counts — kubelets PATCH status on a
cadence; kubemark.HollowCluster produces exactly that), a sweep thread
taints nodes silent past `grace_period` and evicts their pods
(tolerationSeconds staging is not modelled — eviction is immediate, the
zero-tolerations default), and a resumed heartbeat clears the taint.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

from ..api import store as st
from ..api import types as api
from .base import Controller


class NodeLifecycleController(Controller):
    KIND = "Node"

    def __init__(
        self,
        store: st.Store,
        informers,
        grace_period: float = 40.0,
        sweep_interval: float = 5.0,
        clock=time.monotonic,
        workers: int = 1,
    ):
        super().__init__(store, informers, workers=workers)
        self.grace_period = grace_period
        self.sweep_interval = sweep_interval
        self._clock = clock
        self._last_seen: Dict[str, float] = {}
        self._sweeper: threading.Thread = None

    def register(self) -> None:
        self.informers.informer("Node").add_handler(self._on_node)

    def _on_node(self, typ: str, node: api.Node, old) -> None:
        if typ == st.DELETED:
            self._last_seen.pop(node.meta.name, None)
            return
        if old is not None and (
            old.meta.annotations == node.meta.annotations
            and old.status == node.status
        ):
            # spec-only change (e.g. OUR taint/untaint write echoing back)
            # is not a kubelet heartbeat — counting it would clear the
            # unreachable taint one sweep after setting it, forever
            # (observed flapping); heartbeats touch status/annotations
            return
        self._last_seen[node.meta.name] = self._clock()

    def start(self) -> None:
        super().start()
        self._sweeper = threading.Thread(
            target=self._sweep_loop, name="nodelifecycle-sweep", daemon=True
        )
        self._sweeper.start()

    def stop(self) -> None:
        super().stop()
        if self._sweeper:
            self._sweeper.join(timeout=5)

    def _sweep_loop(self) -> None:
        while not self._stop.wait(self.sweep_interval):
            self.sweep()

    def sweep(self) -> None:
        """One monitorNodeHealth pass (exposed for tests/sim drivers)."""
        now = self._clock()
        for name, seen in list(self._last_seen.items()):
            stale = now - seen > self.grace_period
            try:
                node = self.store.get("Node", name, namespace="")
            except st.NotFound:
                continue
            tainted = any(
                t.key == api.TAINT_NODE_UNREACHABLE for t in node.spec.taints
            )
            if stale:
                if not tainted:
                    self._set_taint(name, add=True)
                # level-triggered eviction: pods can land on an
                # already-tainted node (pinned nodeName, in-flight
                # binding, informer lag at the first eviction) — every
                # sweep clears them, like the taint-eviction controller
                self._evict_pods(name)
            elif tainted:
                self._set_taint(name, add=False)

    def _set_taint(self, name: str, add: bool) -> None:
        """Optimistic-concurrency taint edit: re-read + retry instead of
        force-writing a stale object — a forced write would revert
        concurrent heartbeat/label updates (and the revert would then
        count as a heartbeat, flapping the taint)."""
        for _ in range(5):
            try:
                node = self.store.get("Node", name, namespace="")
            except st.NotFound:
                return
            has = any(
                t.key == api.TAINT_NODE_UNREACHABLE for t in node.spec.taints
            )
            if has == add:
                return
            if add:
                node.spec.taints.append(
                    api.Taint(api.TAINT_NODE_UNREACHABLE, "", api.NO_EXECUTE)
                )
            else:
                node.spec.taints = [
                    t for t in node.spec.taints
                    if t.key != api.TAINT_NODE_UNREACHABLE
                ]
            try:
                self.store.update(node)
                return
            except st.Conflict:
                continue
            except st.NotFound:
                return

    def _evict_pods(self, node_name: str) -> None:
        """Taint eviction: delete the silent node's pods unless they
        tolerate unreachable:NoExecute; they requeue and reschedule."""
        pods = self.informers.informer("Pod").list()
        taint = api.Taint(api.TAINT_NODE_UNREACHABLE, "", api.NO_EXECUTE)
        for pod in pods:
            if pod.spec.node_name != node_name:
                continue
            if api.tolerations_tolerate_taint(pod.spec.tolerations, taint):
                continue
            try:
                self.store.delete("Pod", pod.meta.name, pod.meta.namespace)
            except st.NotFound:
                pass

    def sync(self, key: str) -> None:
        """Level-triggered reconcile is the sweep; per-key work is a
        no-op (events only refresh _last_seen)."""
