"""StatefulSet controller: ordered, identity-stable replicas.

Reference: pkg/controller/statefulset/stateful_set_control.go —
replicas are named <set>-<ordinal>; OrderedReady creates ordinal i only
once 0..i-1 are ready and scales down from the highest ordinal;
volumeClaimTemplates materialize one PVC per (template, ordinal) that
survives pod deletion (stable storage identity).  Parallel skips the
ordering gate.  Rolling template updates are delete-and-recreate per
ordinal, highest first, which preserves identity (our simplification of
the partitioned RollingUpdate)."""

from __future__ import annotations

from ..api import store as st
from ..api import types as api
from .base import Controller, split_key
from .deployment import template_hash


class StatefulSetController(Controller):
    KIND = "StatefulSet"

    def register(self) -> None:
        self.informers.informer("StatefulSet").add_handler(self._on_set)
        self.informers.informer("Pod").add_handler(self._on_pod)

    def _on_set(self, typ: str, obj, old) -> None:
        if typ != st.DELETED:
            self.enqueue(obj)

    def _on_pod(self, typ: str, pod, old) -> None:
        self.enqueue_owner(pod, "StatefulSet")

    def _pod_name(self, set_name: str, i: int) -> str:
        return f"{set_name}-{i}"

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        try:
            sts = self.store.get("StatefulSet", name, namespace)
        except st.NotFound:
            return  # GC cascades the pods via ownerReferences
        pods = {
            p.meta.name: p
            for p in self.pods_owned_by(namespace, "StatefulSet", name)
        }
        desired = sts.spec.replicas
        rev = template_hash(sts.spec.template)
        ordered = sts.spec.pod_management_policy != "Parallel"

        # scale down: highest ordinal first, one at a time when ordered
        extra = [
            p for n, p in pods.items()
            if self._ordinal(name, n) is not None
            and self._ordinal(name, n) >= desired
        ]
        if extra:
            victim = max(extra, key=lambda p: self._ordinal(name, p.meta.name))
            self._delete_pod(victim)
            return

        # scale up / recreate missing ordinals FIRST; OrderedReady waits
        # for predecessors before creating the next
        complete = True
        for i in range(desired):
            pod_name = self._pod_name(name, i)
            existing = pods.get(pod_name)
            if existing is not None:
                if ordered and not self._ready(existing):
                    complete = False
                    break  # wait for this ordinal before creating i+1
                continue
            self._create_claims(sts, i)
            self._create_pod(sts, i, rev)
            complete = False
            if ordered:
                break  # one ordinal per reconcile; readiness re-enqueues
        # rolling update: only when every desired ordinal exists (and is
        # ready, when ordered) delete ONE out-of-revision pod, highest
        # ordinal first — each deletion is recreated and readied before
        # the next ordinal is touched, so the set never loses more than
        # one replica to the rollout (stateful_set_control.go's
        # one-at-a-time update walk)
        if complete:
            stale = [
                p for p in pods.values()
                if p.meta.labels.get("statefulset-revision") != rev
            ]
            if stale:
                victim = max(
                    stale,
                    key=lambda p: self._ordinal(name, p.meta.name) or 0,
                )
                self._delete_pod(victim)
        self._write_status(sts, namespace, name)

    @staticmethod
    def _ordinal(set_name: str, pod_name: str):
        prefix = f"{set_name}-"
        if not pod_name.startswith(prefix):
            return None
        try:
            return int(pod_name[len(prefix):])
        except ValueError:
            return None

    @staticmethod
    def _ready(pod: api.Pod) -> bool:
        return bool(pod.spec.node_name) and pod.status.phase == "Running"

    def _delete_pod(self, pod: api.Pod) -> None:
        try:
            self.store.delete("Pod", pod.meta.name, pod.meta.namespace)
        except st.NotFound:
            pass

    def _create_claims(self, sts: api.StatefulSet, i: int) -> None:
        """Per-ordinal PVCs ("<tpl>-<set>-<i>"): created once, NEVER
        deleted with the pod — the stable-storage contract."""
        for tpl in sts.spec.volume_claim_templates:
            claim_name = f"{tpl.meta.name}-{sts.meta.name}-{i}"
            pvc = api.clone(tpl)
            pvc.meta.name = claim_name
            pvc.meta.namespace = sts.meta.namespace
            try:
                self.store.create(pvc)
            except st.AlreadyExists:
                pass  # survives pod churn by design

    def _create_pod(self, sts: api.StatefulSet, i: int, rev: str) -> None:
        template = api.clone(sts.spec.template)
        labels = dict(template.meta.labels)
        labels["statefulset-revision"] = rev
        pod = api.Pod(
            meta=api.ObjectMeta(
                name=self._pod_name(sts.meta.name, i),
                namespace=sts.meta.namespace,
                labels=labels,
                owner_references=[
                    api.OwnerReference(
                        kind="StatefulSet", name=sts.meta.name,
                        uid=sts.meta.uid, controller=True,
                    )
                ],
            ),
            spec=api.clone(template.spec),
        )
        # mount the per-ordinal claims
        for tpl in sts.spec.volume_claim_templates:
            pod.spec.volumes.append(
                api.Volume(
                    name=tpl.meta.name,
                    persistent_volume_claim=(
                        f"{tpl.meta.name}-{sts.meta.name}-{i}"
                    ),
                )
            )
        try:
            self.store.create(pod)
        except st.AlreadyExists:
            pass

    def _write_status(self, sts, namespace, name) -> None:
        pods = self.pods_owned_by(namespace, "StatefulSet", name)
        replicas = len(pods)
        ready = sum(1 for p in pods if self._ready(p))
        if (
            sts.status.replicas == replicas
            and sts.status.ready_replicas == ready
            and sts.status.observed_generation == sts.meta.generation
        ):
            return
        try:
            fresh = self.store.get("StatefulSet", name, namespace)
        except st.NotFound:
            return
        fresh.status.replicas = replicas
        fresh.status.ready_replicas = ready
        fresh.status.observed_generation = fresh.meta.generation
        self.store.update(fresh)
