"""Namespace lifecycle controller: finalize-and-sweep.

Reference: pkg/controller/namespace — a namespace marked for deletion
enters Terminating; the controller deletes every namespaced object in
it via resource discovery, then removes the finalizer so the API server
can drop the Namespace.  Ours mirrors both halves without finalizer
machinery:

  * a Namespace whose status.phase is "Terminating" is swept (every
    kind the store holds, objects in that namespace deleted) and then
    deleted itself;
  * a Namespace DELETE event also sweeps — so `kubectl delete ns` (the
    store-level delete) reaps contents even without the Terminating
    hand-off.
"""

from __future__ import annotations

from ..api import store as st
from ..api import types as api
from .base import Controller

# kinds that are cluster-scoped: never swept by namespace deletion
CLUSTER_SCOPED = set(api.CLUSTER_SCOPED_KINDS)


class NamespaceController(Controller):
    KIND = "Namespace"

    def register(self) -> None:
        self.informers.informer("Namespace").add_handler(self._on_namespace)

    def _on_namespace(self, typ: str, ns: api.Namespace, old) -> None:
        if typ == st.DELETED or ns.status.phase == "Terminating":
            self.queue.add(ns.meta.name)

    def sync(self, key: str) -> None:
        name = key
        self._sweep(name)
        try:
            ns = self.store.get("Namespace", name, namespace="")
        except KeyError:
            return  # already deleted; sweep above finished the job
        if ns.status.phase == "Terminating":
            try:
                self.store.delete("Namespace", name, namespace="")
            except KeyError:
                pass

    def _sweep(self, namespace: str) -> int:
        """Delete every namespaced object in `namespace`; returns count."""
        reaped = 0
        for kind in self.store.kinds():
            if kind in CLUSTER_SCOPED:
                continue
            objs, _ = self.store.list(kind, namespace=namespace)
            for obj in objs:
                try:
                    self.store.delete(kind, obj.meta.name, namespace)
                    reaped += 1
                except KeyError:
                    pass
        return reaped
