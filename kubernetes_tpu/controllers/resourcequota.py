"""ResourceQuota: admission-enforced namespace budgets + the status
controller.

Reference: the quota evaluator wired into admission
(plugin/pkg/admission/resourcequota) rejects creates that would exceed
status.hard, and pkg/controller/resourcequota recomputes status.used
from the live objects.  Tracked resources: "pods" (count),
CPU ("cpu", milli) and MEMORY (bytes) as requests totals — the
pod-centric core of the reference's evaluator registry.
"""

from __future__ import annotations

from typing import Any, Dict

from ..api import admission as adm
from ..api import store as st
from ..api import types as api
from .base import Controller, split_key

TRACKED = ("pods", api.CPU, api.MEMORY)


def _usage_of(pods) -> Dict[str, int]:
    used: Dict[str, int] = {"pods": 0, api.CPU: 0, api.MEMORY: 0}
    for p in pods:
        if p.status.phase in ("Succeeded", "Failed"):
            continue  # terminal pods release their quota (evaluator's
            # QuotaV1Pod scope check)
        used["pods"] += 1
        req = p.resource_requests()
        used[api.CPU] += req.get(api.CPU, 0)
        used[api.MEMORY] += req.get(api.MEMORY, 0)
    return used


def quota_validator(obj: Any, operation: str, store=None) -> None:
    """Admission enforcement: a Pod create that would push any tracked
    resource past a quota's hard limit is rejected with the reference's
    'exceeded quota' error shape."""
    if store is None or operation != "CREATE" or not isinstance(obj, api.Pod):
        return
    quotas = [
        q
        for q in store.list("ResourceQuota")[0]
        if q.meta.namespace == obj.meta.namespace
    ]
    if not quotas:
        return
    pods = [
        p
        for p in store.list("Pod")[0]
        if p.meta.namespace == obj.meta.namespace
    ]
    used = _usage_of(pods)
    req = obj.resource_requests()
    incoming = {
        "pods": 1,
        api.CPU: req.get(api.CPU, 0),
        api.MEMORY: req.get(api.MEMORY, 0),
    }
    for q in quotas:
        for resource, hard in q.spec.hard.items():
            if resource not in TRACKED:
                continue
            would = used.get(resource, 0) + incoming.get(resource, 0)
            if would > hard:
                raise adm.AdmissionError(
                    f"exceeded quota: {q.meta.name}, requested "
                    f"{resource}={incoming.get(resource, 0)}, used "
                    f"{used.get(resource, 0)}, limited {hard}"
                )


quota_validator.wants_store = True


class ResourceQuotaController(Controller):
    """Keeps status.hard/used current (pkg/controller/resourcequota's
    replenishment loop: pod events re-sync the namespace's quotas)."""

    KIND = "ResourceQuota"

    def register(self) -> None:
        self.informers.informer("ResourceQuota").add_handler(self._on_quota)
        self.informers.informer("Pod").add_handler(self._on_pod)

    def _on_quota(self, typ: str, q, old) -> None:
        self.enqueue(q)

    def _on_pod(self, typ: str, pod, old) -> None:
        for q in self.informers.informer("ResourceQuota").list():
            if q.meta.namespace == pod.meta.namespace:
                self.enqueue(q)

    def sync(self, key: str) -> None:
        namespace, name = split_key(key)
        try:
            quota = self.store.get("ResourceQuota", name, namespace)
        except st.NotFound:
            return
        pods = [
            p
            for p in self.informers.informer("Pod").list()
            if p.meta.namespace == namespace
        ]
        used = _usage_of(pods)
        relevant = {
            r: used.get(r, 0) for r in quota.spec.hard if r in TRACKED
        }
        if (
            quota.status.used != relevant
            or quota.status.hard != quota.spec.hard
        ):
            quota.status.hard = dict(quota.spec.hard)
            quota.status.used = relevant
            self.store.update(quota, force=True)
