"""kubernetes_tpu — a TPU-native cluster control plane & batched scheduler.

A ground-up re-design of the capabilities of Kubernetes (reference:
vonsago/kubernetes) around TPU hardware: cluster state is held as dense,
statically-shaped tensors; the scheduler's per-node Filter/Score plugin loop
(reference: pkg/scheduler/schedule_one.go:442-867) becomes a single fused
JAX/XLA solve over (pending_pods x nodes); multi-chip scale-out shards the
node axis over a jax.sharding.Mesh.

Layout (mirrors SURVEY.md section 7):
  api/         object model + in-memory versioned store with watch
               (the etcd + apiserver + apimachinery equivalent)
  client/      informers, listers, workqueues (client-go equivalent)
  ops/         JAX kernels: snapshot tensor schema, filter masks, score
               kernels, batched assignment solves
  parallel/    device-mesh sharding of the solve (shard_map over node axis)
  scheduler/   host-side scheduler: cache, queue, plugin framework, profiles
  controllers/ control loops (replicaset, deployment, job, nodelifecycle, ...)
  perf/        scheduler_perf benchmark harness port
  models/      the flagship end-to-end batched-scheduler "model"
  utils/       vocab/bitset encoding, clocks, backoff
"""

__version__ = "0.1.0"
