"""Runtime lock-order tracker — the dynamic half of the lock-order pass.

The static pass (analysis/lockorder.py) proves the absence of cycles
its conservative call-edge resolver can see; this tracker records the
acquisition edges that ACTUALLY happen while tests run and fails on
inversion: acquiring lock B while holding lock A after some thread has
already acquired A while holding B.

Usage (scoped — the patch is process-global while active):

    from kubernetes_tpu.analysis import runtime as lockorder

    with lockorder.tracked() as tracker:
        ...  # run the scenario
    tracker.assert_no_inversions()

Under pytest, set ``GRAFTLINT_LOCK_ORDER=1`` to arm the tracker for the
whole session (tests/conftest.py wires the fixture); the session fails
if any inversion was recorded.

Locks created while the tracker is installed are wrapped in a
:class:`TrackedLock` proxy named after their allocation site.  Edges
are keyed per lock OBJECT (two-object AB/BA inversions are the
deadlock shape; site-level aggregation would false-positive on
sibling instances of the same class).  The tracker pins a strong
reference to every lock it has seen: edge keys are ``id()``s, and a
garbage-collected lock's id being REUSED by a fresh lock would
otherwise stitch two unrelated objects into one phantom AB/BA cycle
(tests construct thousands of short-lived stores and watches — the
few bytes per pinned lock are the price of sound identities).
Reentrant re-acquisition is ignored.  The proxy forwards the private
``_is_owned`` / ``_release_save`` / ``_acquire_restore`` hooks so
``threading.Condition`` built on a tracked (R)Lock keeps working.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
from typing import Dict, List, Optional, Tuple


class LockOrderViolation(AssertionError):
    """Two locks were acquired in both orders (potential deadlock)."""


class LockOrderTracker:
    def __init__(self):
        # edges[(id_a, id_b)] = (name_a, name_b, where) — a held while
        # acquiring b.  The tracker's own mutex is a raw lock created
        # BEFORE install() patches the factories, so it is never tracked.
        self._mu = threading.Lock()
        self._edges: Dict[Tuple[int, int], Tuple[str, str, str]] = {}
        # id -> the lock object itself: pinning every seen lock keeps
        # its id from being reused by a later allocation (see module
        # docstring — unpinned ids produced phantom cross-object cycles)
        self._refs: Dict[int, object] = {}
        self._tl = threading.local()
        self.inversions: List[str] = []

    # -- held-stack bookkeeping (per thread) -------------------------------

    def _held(self) -> List[Tuple[int, str]]:
        stack = getattr(self._tl, "stack", None)
        if stack is None:
            stack = self._tl.stack = []
        return stack

    def before_acquire(
        self, lock_id: int, name: str, ref: object = None
    ) -> None:
        held = self._held()
        if any(lid == lock_id for lid, _ in held):
            return  # reentrant
        with self._mu:
            if ref is not None:
                self._refs.setdefault(lock_id, ref)
            for held_id, held_name in held:
                edge = (held_id, lock_id)
                back = (lock_id, held_id)
                if back in self._edges and edge not in self._edges:
                    a_name, b_name, where = self._edges[back]
                    self.inversions.append(
                        f"lock-order inversion: acquiring '{name}' while "
                        f"holding '{held_name}', but '{b_name}' was "
                        f"previously acquired while holding '{a_name}' "
                        f"(first order seen at {where}; now at "
                        f"{_caller_site(3, frames=6)})"
                    )
                self._edges.setdefault(
                    edge, (held_name, name, _caller_site(3, frames=6))
                )

    def on_acquired(self, lock_id: int, name: str) -> None:
        self._held().append((lock_id, name))

    def on_release(self, lock_id: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == lock_id:
                del held[i]
                return

    # -- results -----------------------------------------------------------

    def edges(self) -> List[Tuple[str, str]]:
        with self._mu:
            return [(a, b) for (a, b, _) in self._edges.values()]

    def assert_no_inversions(self) -> None:
        if self.inversions:
            raise LockOrderViolation(
                "\n".join(self.inversions[:20])
                + (
                    f"\n... and {len(self.inversions) - 20} more"
                    if len(self.inversions) > 20
                    else ""
                )
            )


def _caller_site(depth: int, frames: int = 1) -> str:
    """`frames` == 1 gives the allocation-site label locks are named
    with; inversion reports pass more to capture the calling chain —
    'watch_stats <- test_helper' localizes an AB/BA pair in one read
    where a bare file:line pointing into a lock proxy cannot."""
    out = []
    try:
        f = sys._getframe(depth)
        for _ in range(frames):
            if f is None:
                break
            out.append(
                f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
                + (f":{f.f_code.co_name}" if frames > 1 else "")
            )
            f = f.f_back
    except ValueError:
        pass
    return " <- ".join(out) or "<unknown>"


class TrackedLock:
    """Duck-typed proxy over a real Lock/RLock recording acquisition
    order.  Reentrant acquires are transparent to the tracker."""

    def __init__(self, inner, name: str, tracker: LockOrderTracker):
        self._inner = inner
        self._name = name
        self._tracker = tracker

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._tracker.before_acquire(id(self), self._name, ref=self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._tracker.on_acquired(id(self), self._name)
        return got

    def release(self):
        self._tracker.on_release(id(self))
        return self._inner.release()

    def locked(self):
        return self._inner.locked()

    def _at_fork_reinit(self):
        # modules captured at import time wire this into
        # os.register_at_fork (concurrent.futures.thread's global
        # shutdown lock) — a proxy without it breaks any IMPORT that
        # happens inside a tracked window
        return self._inner._at_fork_reinit()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # threading.Condition integration: forward the private hooks when the
    # inner lock has them (RLock), with coarse stack bookkeeping
    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        self._tracker.on_release(id(self))
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        self._tracker.before_acquire(id(self), self._name, ref=self)
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._tracker.on_acquired(id(self), self._name)

    def __repr__(self):
        return f"<TrackedLock {self._name} {self._inner!r}>"


_active: Optional[LockOrderTracker] = None


@contextlib.contextmanager
def tracked(tracker: Optional[LockOrderTracker] = None):
    """Install lock tracking for the dynamic extent of the context:
    every threading.Lock/RLock CREATED inside is wrapped.  Pre-existing
    locks are untouched (they predate the window and cannot participate
    in a fresh inversion pair with each other being tracked)."""
    global _active
    if _active is not None:
        # nested arming shares the outer tracker (session fixture +
        # per-test use must not double-patch)
        yield _active
        return
    tracker = tracker or LockOrderTracker()
    real_lock, real_rlock = threading.Lock, threading.RLock

    def make_lock():
        return TrackedLock(real_lock(), f"Lock@{_caller_site(2)}", tracker)

    def make_rlock():
        return TrackedLock(real_rlock(), f"RLock@{_caller_site(2)}", tracker)

    threading.Lock = make_lock
    threading.RLock = make_rlock
    _active = tracker
    try:
        yield tracker
    finally:
        threading.Lock = real_lock
        threading.RLock = real_rlock
        _active = None


def wrap(lock, name: str, tracker: LockOrderTracker) -> TrackedLock:
    """Explicitly wrap an existing lock (tests that build their own
    scenario without the global patch)."""
    return TrackedLock(lock, name, tracker)
