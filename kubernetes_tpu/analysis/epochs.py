"""Runtime epoch auditor — the dynamic half of graftcoh (coherence).

The static pass (analysis/coherence.py) proves every device-resident
cache is WIRED into the discipline surfaces (speculation rollback,
leader-reconcile invalidate, the finalize_pending heal wire, a chaos
fault point).  This auditor observes the residents that ACTUALLY reach
a solve and answers the question the wiring proof cannot: do the
buffers the solve consumes carry epochs consistent with the scheduler
cache's current generations?

Every resident buffer is stamped with an :class:`EpochStamp` at each
state transition (sync / rollback / invalidate — models/mirror.py and
models/partials.py own the stamping):

    (struct_generation, vocab watermark, dirty watermark, buffer lineage)

``struct_gen`` is ClusterState.struct_generation (resource-axis
identity), ``vocab_key`` the per-referenced-key expansion watermark
(None for residents that do not expand against vocabularies),
``synced_gen`` the ClusterState.generation the buffer content matches
(the dirty watermark), and ``buffer_id`` a process-unique lineage token
minted at every full upload/recompute — a delta chain keeps its base's
lineage, a rollback restores the bookmarked one, an invalidate clears
the stamp whole.

Armed, the auditor validates at consume time — inside
``TPUBatchScheduler.encode_pending`` (against the cache's CURRENT
generations, under the cache lock) and ``_dispatch`` (cross-resident:
the partials epoch must agree with the mirror epoch the solve reads) —
and fails loudly with the divergent ``(resident, field, epoch)``
triple.  Disarmed cost is one module-global None check per hook.

Usage (scoped, mirroring analysis/retrace.py)::

    from kubernetes_tpu.analysis import epochs

    with epochs.tracked() as auditor:
        ...                      # scheduler runs, hooks audit
    auditor.assert_clean()

Under pytest, set ``GRAFTLINT_COHERENCE=1`` to arm the auditor for the
whole session (tests/conftest.py wires the fixture, exactly like
GRAFTLINT_LOCK_ORDER / GRAFTLINT_SHAPES); bench.py arms it per run and
``BENCH_STRICT=1`` fails on any violation.  The scheduler mirrors
:func:`audits_total` / :func:`violations_total` into the
``scheduler_coherence_audits_total`` /
``scheduler_coherence_violations_total`` gauges each cycle.

This module is import-light (no JAX): stamps are plain ints/tuples and
the hooks never touch device array contents.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
from typing import List, NamedTuple, Optional


class CoherenceViolation(AssertionError):
    """A resident buffer reached a solve with a divergent epoch."""


class EpochStamp(NamedTuple):
    """Epoch tuple stamped onto a resident buffer at each transition."""

    resident: str                  # "mirror" / "partials" / ...
    struct_gen: int                # ClusterState.struct_generation
    vocab_key: Optional[tuple]     # expansion watermark (None: no vocab)
    synced_gen: int                # ClusterState.generation (dirty mark)
    buffer_id: int                 # lineage: minted per full upload


# process-unique buffer lineage tokens; 0 is reserved for "no buffer"
_buffer_ids = itertools.count(1)


def fresh_buffer_id() -> int:
    """Mint a lineage token for a freshly (re)built resident buffer."""
    return next(_buffer_ids)


class EpochAuditor:
    def __init__(self):
        self._mu = threading.Lock()
        self.audits = 0
        self.violations: List[str] = []
        # accounting, not violations: rollbacks refused because the
        # resident was invalidated after the bookmark (the guard that
        # keeps a rollback from resurrecting a buffer an invalidate
        # deliberately dropped — models/mirror.py rollback())
        self.rollbacks_blocked = 0

    # -- recording ---------------------------------------------------------

    def _violate(self, resident: str, field: str, epoch, expected) -> None:
        self.violations.append(
            f"({resident}, {field}, {epoch!r}): diverges from the "
            f"scheduler cache's current {field}={expected!r} at consume "
            "time — a discipline wire (rollback/invalidate/sync) was "
            "missed"
        )

    def audit_consume(
        self,
        stamp: Optional[EpochStamp],
        resident: str,
        struct_gen: int,
        generation: int,
        vocab_key: Optional[tuple] = None,
        check_vocab: bool = False,
    ) -> None:
        """One consume-time audit of a resident's stamp against the
        owning cache's CURRENT generations (caller holds the cache
        lock — the generations are read there)."""
        with self._mu:
            self.audits += 1
            if stamp is None:
                self.violations.append(
                    f"({resident}, stamp, None): resident buffer consumed "
                    "with no epoch stamp — it was never synced, or an "
                    "invalidate cleared it and a stale reference leaked"
                )
                return
            if stamp.struct_gen != struct_gen:
                self._violate(resident, "struct_gen", stamp, struct_gen)
            if stamp.synced_gen != generation:
                self._violate(resident, "synced_gen", stamp, generation)
            if check_vocab and stamp.vocab_key != vocab_key:
                self._violate(resident, "vocab_key", stamp, vocab_key)

    def audit_pair(
        self, mirror_stamp: EpochStamp, partials_stamp: EpochStamp
    ) -> None:
        """Cross-resident audit at dispatch time: the partials rows a
        solve consumes must have been evaluated in the same epoch as
        the mirror tensors it consumes (the two residents roll
        together — scheduler._misspeculate_group)."""
        with self._mu:
            self.audits += 1
            if partials_stamp.struct_gen != mirror_stamp.struct_gen:
                self._violate(
                    "partials", "struct_gen", partials_stamp,
                    mirror_stamp.struct_gen,
                )
            if partials_stamp.synced_gen != mirror_stamp.synced_gen:
                self._violate(
                    "partials", "synced_gen", partials_stamp,
                    mirror_stamp.synced_gen,
                )

    def note_rollback_blocked(self, resident: str) -> None:
        with self._mu:
            self.rollbacks_blocked += 1

    # -- results -----------------------------------------------------------

    @property
    def audits_total(self) -> int:
        with self._mu:
            return self.audits

    @property
    def violations_total(self) -> int:
        with self._mu:
            return len(self.violations)

    def assert_clean(self) -> None:
        if self.violations:
            raise CoherenceViolation("\n".join(self.violations[:20]))


_active: Optional[EpochAuditor] = None


@contextlib.contextmanager
def tracked(auditor: Optional[EpochAuditor] = None):
    """Arm epoch auditing for the dynamic extent of the context.
    Nested arming shares the outer auditor (session fixture + per-test
    use must not shadow each other — analysis/retrace.py, same)."""
    global _active
    if _active is not None:
        yield _active
        return
    auditor = auditor or EpochAuditor()
    _active = auditor
    try:
        yield auditor
    finally:
        _active = None


def active() -> Optional[EpochAuditor]:
    return _active


# -- module-level hooks (no-ops unless armed) --------------------------------

def audit_mirror(mirror, state) -> None:
    """Consume-time audit of a DeviceClusterMirror: called from
    encode_pending right after mirror.sync(), under the cache lock."""
    a = _active
    if a is not None:
        a.audit_consume(
            mirror.epoch(), "mirror",
            state.struct_generation, state.generation,
        )


def audit_partials(partials, state) -> None:
    """Consume-time audit of a PartialsCache: called from
    encode_pending right after partials.sync(), under the cache lock.
    Skips cleanly when the cache declined the batch (no stamp and no
    store is a cold solve, not a violation)."""
    a = _active
    if a is None:
        return
    if partials.epoch() is None and partials._store is None:
        return  # declined / cold: the solve takes the in-program path
    a.audit_consume(
        partials.epoch(), "partials",
        state.struct_generation, state.generation,
        vocab_key=partials._vocab_watermark(), check_vocab=True,
    )


def audit_dispatch(meta) -> None:
    """Dispatch-time cross-resident audit: the epoch pair encode_pending
    stamped onto the SnapshotMeta must agree with itself — the partials
    statics a solve reads were evaluated against the exact mirror epoch
    it consumes."""
    a = _active
    if a is None:
        return
    stamp = getattr(meta, "coherence_stamp", None)
    if stamp is None:
        return  # cold encode, or stamped before arming
    mirror_stamp, partials_stamp = stamp
    if mirror_stamp is not None and partials_stamp is not None:
        a.audit_pair(mirror_stamp, partials_stamp)


def note_rollback_blocked(resident: str) -> None:
    a = _active
    if a is not None:
        a.note_rollback_blocked(resident)


def audits_total() -> int:
    a = _active
    return a.audits_total if a is not None else 0


def violations_total() -> int:
    a = _active
    return a.violations_total if a is not None else 0
