"""recompile-discipline: no kernel argument may trigger an unexpected
XLA retrace.

The perf stack's whole compile story (wavefront solve, prewarm pool,
persistent compile cache) rests on one discipline: every array entering
a ``@hot_path`` kernel is padded onto the power-of-two bucket lattice
(utils.vocab.pad_dim / pad_constraint_dim) with the dtypes the schema
contracts declare, so the set of XLA compile keys a workload generates
is exactly the bucket set.  A single un-bucketed dimension or silently
promoted dtype re-traces XLA and eats a 10-40 s compile on the hot
path.  This pass PROVES the discipline by abstract interpretation:

  encode     real ``SnapshotBuilder`` encodes at awkward raw sizes must
             land exactly on the lattice: every array unifies with its
             contract (analysis/contracts.py) under an axis environment
             where ``N``/``P`` are pinned to their pad buckets and
             free row axes must be constraint buckets;
  kernels    every solver kernel (greedy / wavefront / auction) driven
             through ``jax.eval_shape`` over contract-built abstract
             snapshots across the lattice must yield outputs matching
             the result contracts at every bucket — dtype-stable, no
             shape that depends on anything but the bucket;
  closure    the abstract input signatures (the compile keys) must be
             exactly one per lattice point, and the lattice must be
             closed under the gang-admission-retry subset solves
             (``num_pods_hint`` pins every binary-search subset into
             the full batch's bucket).

This module imports JAX and therefore runs as its own CLI mode
(``python -m kubernetes_tpu.analysis --shapes`` / ``make lint-shapes``)
and tier-1 test (tests/test_shapes.py), keeping ``make lint``
import-light.  The runtime complement is analysis/retrace.py: a
``GRAFTLINT_SHAPES=1``-armable tracker counting the retraces that
actually happen while tests and benches run.
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

from . import Finding, load_sources
from . import contracts as ct

CHECK = "recompile-discipline"

#: (node bucket, pod bucket) lattice the kernels are driven across.
#: Small buckets on purpose: eval_shape is tracing-only, but the solver
#: scan bodies are large programs.
LATTICE: Tuple[Tuple[int, int], ...] = ((8, 8), (16, 8), (16, 16), (32, 16))

#: raw (nodes, pods) sizes the encoder is validated at — deliberately
#: NOT powers of two (landing on the lattice is the encoder's doing)
#: and with n/p in DIFFERENT buckets, so an N/P axis swap cannot hide
ENCODE_SIZES: Tuple[Tuple[int, int], ...] = ((3, 12), (20, 2))

#: representative raw batch sizes for the gang-retry closure check
GANG_RETRY_SIZES: Tuple[int, ...] = (5, 8, 100, 1024)

#: (node, victim-slot, priority-level, pod) buckets the batched
#: preemption kernel is driven across (ops/preemption.py
#: batched_dry_run); the encoder pads with pad_dim(n, 8) / pad_dim(k, 4)
#: / pad_dim(l, 1) / pad_dim(p, 4) — see scheduler/preemption.py
PREEMPT_LATTICE: Tuple[Tuple[int, int, int, int], ...] = (
    (8, 4, 1, 4), (16, 4, 1, 4), (16, 4, 2, 8), (32, 8, 2, 8),
)

#: raw (candidate nodes, victims, levels, pods) sizes the preemption
#: encoder must land on the lattice from (closure check)
PREEMPT_RAW_SIZES: Tuple[Tuple[int, int, int, int], ...] = (
    (3, 1, 1, 2), (20, 5, 3, 9), (300, 17, 4, 16),
)


def _schema_contracts(root: str, package: str = "kubernetes_tpu"):
    files = load_sources(root, [os.path.join(package, "ops")])
    contracts: List[ct.Contract] = []
    for src in files:
        got, _issues = ct.collect(src)  # presence is tensor-contract's job
        contracts.extend(got)
    return ct.index_by_class(contracts)


# -- axis environments -------------------------------------------------------

def _class_env(
    cls: str, limits, n: int, p: int, rows: Dict[str, int]
) -> Dict[str, int]:
    """Concrete axis environment for one schema class.  ``rows`` sets
    the free constraint-row axes (default 1 = the no-constraints
    bucket); everything else derives from SnapshotLimits — the same
    derivations SnapshotBuilder uses, so drift fails the unify step."""
    from ..ops import schema

    r = rows.get("R", len(schema.FIXED_RESOURCES))
    tk = len(limits.topology_keys)
    common = {"N": n, "P": p, "R": r, "TK": tk}
    if cls == "ClusterTensors":
        return {
            **common,
            "LW": limits.label_words,
            "TW": limits.taint_words,
            "PW": limits.port_words,
            "IW": limits.image_words,
        }
    if cls == "SelectorTable":
        return {
            "S": rows.get("S", 1),
            "T": limits.max_terms,
            "E": limits.max_exprs,
            "K": limits.max_ids_per_expr,
        }
    if cls == "PreferredTable":
        return {
            "F": rows.get("F", 1),
            "E": limits.max_exprs,
            "K": limits.max_ids_per_expr,
        }
    if cls == "SpreadTable":
        return {**common, "C": rows.get("C", 1), "MC": limits.max_spread_per_pod}
    if cls == "TermTable":
        return {**common, "T": rows.get("T", 1), "MA": limits.max_pod_terms}
    if cls == "PodBatch":
        c = rows.get("classes", 1)
        return {
            **common,
            "TW": limits.taint_words,
            "PW": limits.port_words,
            "MT": limits.max_preferred,
            "C": c,
            "Cs": c,
            "Cc": rows.get("cons_classes", 1),
        }
    if cls == "PrefPodTable":
        return {**common, "U": rows.get("U", 1), "MA": limits.max_pod_terms}
    if cls == "ImageTable":
        return {**common, "I_pad": rows.get("I", 1), "MI": limits.max_pod_images}
    raise KeyError(f"no axis environment for schema class {cls}")


def _snapshot_classes():
    """Snapshot field name -> component class (resolved, not the string
    annotations)."""
    import typing

    from ..ops import schema

    hints = typing.get_type_hints(schema.Snapshot)
    return {f: hints[f] for f in schema.Snapshot._fields}


def abstract_snapshot(
    byclass, limits=None, n: int = 8, p: int = 8,
    rows: Optional[Dict[str, int]] = None,
):
    """A Snapshot of ShapeDtypeStructs built FROM the contracts — the
    contracts drive eval_shape, so schema/contract drift fails loudly."""
    import jax
    import numpy as np

    from ..ops import schema

    limits = limits or schema.SnapshotLimits()
    rows = rows or {}
    parts = {}
    for field, cls in _snapshot_classes().items():
        env = _class_env(cls.__name__, limits, n, p, rows)
        cfields = byclass.get(cls.__name__, {})
        vals = {}
        for f in cls._fields:
            c = cfields.get(f)
            if c is None:
                raise KeyError(
                    f"{cls.__name__}.{f} has no parsed contract (run the "
                    "tensor-contract pass first)"
                )
            vals[f] = jax.ShapeDtypeStruct(c.shape(env), np.dtype(c.dtype))
        parts[field] = cls(**vals)
    return schema.Snapshot(**parts)


# -- unification (real arrays vs contracts) ----------------------------------

def _is_pow2(x: int) -> bool:
    from ..utils.vocab import is_pad_bucket

    return is_pad_bucket(x, 1)


def _constraint_bucket_ok(x: int) -> bool:
    """pad_constraint_dim's range: 1 (no rows) or a power of two >= 32."""
    from ..utils.vocab import is_constraint_bucket

    return is_constraint_bucket(x)


def _unify_table(
    table, cfields: Dict[str, ct.Contract], env: Dict[str, int],
    free_row_axes: Sequence[str], where: str, findings: List[Finding],
    file: str, pow2_axes: Sequence[str] = (),
) -> None:
    """Check every array (or abstract ShapeDtypeStruct) of one table
    against its contract, binding free axes on first sight and requiring
    consistency afterwards.  ``free_row_axes`` must land on
    pad_constraint_dim buckets; ``pow2_axes`` on pad_dim(x, 1) buckets
    (the pod-class axes)."""
    env = dict(env)
    pend: List[Tuple[ct.Axis, int, str, int]] = []
    for f in type(table)._fields:
        arr = getattr(table, f)
        c = cfields.get(f)
        if c is None or arr is None or not hasattr(arr, "shape"):
            continue
        a = arr
        sym = f"{c.cls}.{f}"
        if str(a.dtype) != c.dtype:
            findings.append(
                Finding(
                    CHECK, file, c.line, sym,
                    f"{where}: dtype {a.dtype} != contract {c.render()}",
                )
            )
        if len(a.shape) != c.rank:
            findings.append(
                Finding(
                    CHECK, file, c.line, sym,
                    f"{where}: rank {len(a.shape)} != contract {c.render()}",
                )
            )
            continue
        for j, (axis, dim) in enumerate(zip(c.axes, a.shape)):
            if axis.sym is None:
                if dim != axis.const:
                    findings.append(
                        Finding(
                            CHECK, file, c.line, sym,
                            f"{where}: axis {j} = {dim}, contract "
                            f"{c.render()} pins it to {axis.const}",
                        )
                    )
                continue
            if axis.ceil:
                pend.append((axis, dim, sym, c.line))
                continue
            bound = env.get(axis.sym)
            if bound is None:
                env[axis.sym] = dim
                if axis.sym in free_row_axes and not _constraint_bucket_ok(dim):
                    findings.append(
                        Finding(
                            CHECK, file, c.line, sym,
                            f"{where}: free row axis {axis.sym} = {dim} is "
                            "not a pad_constraint_dim bucket (1 or a power "
                            "of two >= 32) — this shape recompiles per "
                            "composition",
                        )
                    )
                elif axis.sym in pow2_axes and not _is_pow2(dim):
                    findings.append(
                        Finding(
                            CHECK, file, c.line, sym,
                            f"{where}: free axis {axis.sym} = {dim} is not "
                            "a pad_dim power-of-two bucket — this shape "
                            "recompiles per composition",
                        )
                    )
            elif bound != dim:
                findings.append(
                    Finding(
                        CHECK, file, c.line, sym,
                        f"{where}: axis {axis.sym} = {dim} but {axis.sym} = "
                        f"{bound} elsewhere (contract {c.render()})",
                    )
                )
    for axis, dim, sym, line in pend:
        base = env.get(axis.sym)
        if base is None:
            continue
        want = math.ceil(base / axis.const)
        if dim != want:
            findings.append(
                Finding(
                    CHECK, file, line, sym,
                    f"{where}: ceil({axis.sym}/{axis.const}) = {want} "
                    f"(from {axis.sym}={base}), got {dim}",
                )
            )


#: Snapshot component class -> free (encode-determined) row axes that
#: must land on pad_constraint_dim buckets
_FREE_ROW_AXES = {
    "ClusterTensors": (),
    "SelectorTable": ("S",),
    "PreferredTable": ("F",),
    "SpreadTable": ("C",),
    "TermTable": ("T",),
    "PodBatch": (),
    "PrefPodTable": ("U",),
    "ImageTable": (),
}

#: free axes padded with pad_dim(x, 1): any power of two (pod-class and
#: image-vocab axes)
_POW2_AXES = {
    "PodBatch": ("C", "Cs", "Cc"),
    "ImageTable": ("I_pad",),
}


def _check_encode(byclass, findings: List[Finding]) -> None:
    """Real SnapshotBuilder encodes at awkward raw sizes must land on
    the lattice with contract dtypes everywhere."""
    from ..api import types as api
    from ..ops import schema
    from ..testing.wrappers import GI, MI, make_node, make_pod
    from ..utils import vocab as vb

    file = "kubernetes_tpu/ops/schema.py"
    for raw_n, raw_p in ENCODE_SIZES:
        builder = schema.SnapshotBuilder()
        nodes = [
            make_node(f"n{i}")
            .capacity(cpu_milli=4000, mem=8 * GI, pods=16)
            .zone(f"z{i % 2}")
            .obj()
            for i in range(raw_n)
        ]
        pods = []
        for i in range(raw_p):
            pw = (
                make_pod(f"p{i}")
                .req(cpu_milli=100, mem=128 * MI)
                .label("app", f"svc-{i % 2}")
            )
            if i % 2 == 0:
                pw.spread(
                    1, api.LABEL_ZONE, "DoNotSchedule", {"app": f"svc-{i % 2}"}
                )
            else:
                pw.pod_anti_affinity(
                    {"app": f"svc-{i % 2}"}, api.LABEL_HOSTNAME
                )
            pods.append(pw.obj())
        snap, meta = builder.build(nodes, pods)
        lim = builder.limits
        n_pad = vb.pad_dim(raw_n, lim.min_nodes)
        p_pad = vb.pad_dim(raw_p, lim.min_pods)
        rows = {"R": len(meta.resource_names)}
        for field, table in zip(type(snap)._fields, snap):
            cls = type(table).__name__
            env = _class_env(cls, lim, n_pad, p_pad, rows)
            # free axes bind to what the encoder produced; drop their
            # seeded defaults so unify sees them as free
            free = _FREE_ROW_AXES.get(cls, ())
            pow2 = _POW2_AXES.get(cls, ())
            env = {
                k: v for k, v in env.items()
                if k not in free and k not in pow2
            }
            _unify_table(
                table, byclass.get(cls, {}), env, free,
                f"encode[{raw_n}x{raw_p}].{field}", findings, file,
                pow2_axes=pow2,
            )


def _result_contract_check(
    result, cls_name: str, byclass, env: Dict[str, int], where: str,
    findings: List[Finding], file: str,
) -> None:
    """eval_shape output vs the result NamedTuple's contracts; component
    tables (SolveResult.cluster) recurse into their own contracts."""
    cfields = byclass.get(cls_name, {})
    for f in type(result)._fields:
        val = getattr(result, f)
        if val is None:
            continue
        c = cfields.get(f)
        if c is None:
            sub = type(val).__name__
            if sub in byclass:
                sub_env = {
                    k: env[k] for k in ("N", "P", "R", "TK", "LW", "TW",
                                        "PW", "IW") if k in env
                }
                _unify_table(
                    val, byclass[sub], sub_env, (), f"{where}.{f}",
                    findings, file,
                )
            continue
        want_shape = c.shape(env)
        if tuple(val.shape) != want_shape or str(val.dtype) != c.dtype:
            findings.append(
                Finding(
                    CHECK, file, c.line, f"{cls_name}.{f}",
                    f"{where}: eval_shape output {val.dtype}"
                    f"{tuple(val.shape)} != contract {c.render()} "
                    f"(= {c.dtype}{want_shape})",
                )
            )


def _check_kernels(byclass, findings: List[Finding]) -> None:
    """Drive the three solver kernels through eval_shape across the
    lattice; outputs must match the result contracts at every bucket
    and the abstract signature set must be exactly one per call."""
    import jax

    from ..ops import assign, auction, schema
    from . import retrace

    limits = schema.SnapshotLimits()
    ff_off = assign.FeatureFlags()

    def env_for(n, p, rows=None):
        env = _class_env("ClusterTensors", limits, n, p, rows or {})
        return env

    signatures = {"greedy": set(), "wavefront": set(), "auction": set()}
    calls = {"greedy": 0, "wavefront": 0, "auction": 0}

    for n, p in LATTICE:
        snap = abstract_snapshot(byclass, limits, n=n, p=p)

        # greedy scan
        calls["greedy"] += 1
        signatures["greedy"].add(
            retrace.signature(snap, (1, ff_off, 0))
        )
        try:
            res = jax.eval_shape(
                lambda s: assign.greedy_assign(
                    s, topo_z=1, features=ff_off, n_groups=0
                ),
                snap,
            )
            _result_contract_check(
                res, "SolveResult", byclass, env_for(n, p),
                f"greedy[{n}x{p}]", findings, "kubernetes_tpu/ops/assign.py",
            )
        except Exception as e:  # noqa: BLE001 — abstract eval failed
            findings.append(
                Finding(
                    CHECK, "kubernetes_tpu/ops/assign.py", 1,
                    "greedy_assign",
                    f"eval_shape failed at bucket {n}x{p}: {e}",
                )
            )

        # wavefront (wave plan is a device arg: i32[W_pad, K], the
        # same shape plan_waves pads to)
        from ..utils.vocab import pad_dim

        w_pad = pad_dim(max(-(-p // assign.DEFAULT_WAVE_CAP), 1), 8)
        members = jax.ShapeDtypeStruct(
            (w_pad, assign.DEFAULT_WAVE_CAP), "int32"
        )
        calls["wavefront"] += 1
        signatures["wavefront"].add(
            retrace.signature((snap, members), (1, ff_off, 0))
        )
        try:
            res = jax.eval_shape(
                lambda s, m: assign.wavefront_assign(
                    s, m, topo_z=1, features=ff_off, n_groups=0
                ),
                snap, members,
            )
            _result_contract_check(
                res, "SolveResult", byclass, env_for(n, p),
                f"wavefront[{n}x{p}]", findings,
                "kubernetes_tpu/ops/assign.py",
            )
        except Exception as e:  # noqa: BLE001
            findings.append(
                Finding(
                    CHECK, "kubernetes_tpu/ops/assign.py", 1,
                    "wavefront_assign",
                    f"eval_shape failed at bucket {n}x{p}: {e}",
                )
            )

        # auction (joint solve)
        tie_k = min(64, n)
        calls["auction"] += 1
        signatures["auction"].add(
            retrace.signature(snap, (0, ff_off, (1, 1), tie_k))
        )
        try:
            res = jax.eval_shape(
                lambda s: auction.auction_assign(
                    s, n_groups=0, features=ff_off, topo_z=(1, 1),
                    tie_k=tie_k,
                ),
                snap,
            )
            _result_contract_check(
                res, "AuctionResult", byclass, env_for(n, p),
                f"auction[{n}x{p}]", findings,
                "kubernetes_tpu/ops/auction.py",
            )
        except Exception as e:  # noqa: BLE001
            findings.append(
                Finding(
                    CHECK, "kubernetes_tpu/ops/auction.py", 1,
                    "auction_assign",
                    f"eval_shape failed at bucket {n}x{p}: {e}",
                )
            )

    # a constraint-family flip IS a distinct compile key (the prewarm
    # pool compiles the flipped variant for exactly this reason): the
    # spread-enabled signature must differ from the base one
    n, p = 16, 16
    snap_sp = abstract_snapshot(
        byclass, limits, n=n, p=p, rows={"C": 32}
    )
    ff_sp = assign.FeatureFlags(spread=True, spread_slots=(1,))
    sig_sp = retrace.signature(snap_sp, (8, ff_sp, 0))
    if sig_sp in signatures["greedy"]:
        findings.append(
            Finding(
                CHECK, "kubernetes_tpu/ops/assign.py", 1, "greedy_assign",
                "spread-enabled signature collides with a base-lattice "
                "compile key (feature flags must be part of the key)",
            )
        )
    try:
        res = jax.eval_shape(
            lambda s: assign.greedy_assign(
                s, topo_z=8, features=ff_sp, n_groups=0
            ),
            snap_sp,
        )
        _result_contract_check(
            res, "SolveResult", byclass, env_for(n, p),
            f"greedy+spread[{n}x{p}]", findings,
            "kubernetes_tpu/ops/assign.py",
        )
    except Exception as e:  # noqa: BLE001
        findings.append(
            Finding(
                CHECK, "kubernetes_tpu/ops/assign.py", 1, "greedy_assign",
                f"eval_shape (spread features) failed at {n}x{p}: {e}",
            )
        )

    for label, sigs in signatures.items():
        if len(sigs) != calls[label]:
            findings.append(
                Finding(
                    CHECK, "kubernetes_tpu/ops/assign.py", 1, label,
                    f"{calls[label]} lattice points produced "
                    f"{len(sigs)} distinct compile keys — the abstract "
                    "signature set must be exactly the bucket set",
                )
            )


#: (slice-count, torus-extent) buckets the slice carve-out kernels are
#: driven across (ops/slices.py; features.slice_z / slice_dim are both
#: pad_dim powers of two, so they stay on the executable-key lattice)
SLICE_LATTICE: Tuple[Tuple[int, int], ...] = ((1, 2), (2, 2), (4, 4))


def _check_slice_kernels(byclass, findings: List[Finding]) -> None:
    """Slice carve-out coverage: the greedy solver with the slice family
    armed must eval_shape across the (slice_z, slice_dim) lattice with
    contract-stable SolveResult outputs (carve-out telemetry scalars
    included), one compile key per bucket, distinct from the base keys
    — and the sharded twin's keys distinct from the single-chip ones.
    The standalone fragmentation kernel is checked against the
    SliceStats contracts at every bucket."""
    import jax
    import numpy as np

    from ..ops import assign, schema
    from ..ops import slices as slices_ops
    from ..parallel import sharded
    from . import retrace

    file = "kubernetes_tpu/ops/slices.py"
    limits = schema.SnapshotLimits()
    n, p = 16, 8
    snap = abstract_snapshot(byclass, limits, n=n, p=p)
    stats_fields = byclass.get("SliceStats", {})
    if not stats_fields:
        findings.append(
            Finding(
                CHECK, file, 1, "SliceStats",
                "slice-stats contracts missing (run the tensor-contract "
                "pass first)",
            )
        )
        return

    base_sig = retrace.signature(snap, (1, assign.FeatureFlags(), 0))
    sigs = set()
    for policy_require in (False, True):
        for sz, sd in SLICE_LATTICE:
            ff = assign.FeatureFlags(
                slices=True, slice_require=policy_require,
                slice_z=sz, slice_dim=sd,
            )
            sig = retrace.signature(snap, (1, ff, 4))
            sigs.add(sig)
            if sig == base_sig:
                findings.append(
                    Finding(
                        CHECK, file, 1, "carveout_eval",
                        "slice-enabled compile key collides with the base "
                        "key (slice feature flags must be part of the key)",
                    )
                )
            try:
                res = jax.eval_shape(
                    lambda s, ff=ff: assign.greedy_assign(
                        s, topo_z=1, features=ff, n_groups=4
                    ),
                    snap,
                )
            except Exception as e:  # noqa: BLE001 — abstract eval failed
                findings.append(
                    Finding(
                        CHECK, file, 1, "carveout_eval",
                        f"eval_shape failed at slice bucket "
                        f"{sz}x{sd} (require={policy_require}): {e}",
                    )
                )
                continue
            env = _class_env("ClusterTensors", limits, n, p, {})
            _result_contract_check(
                res, "SolveResult", byclass, env,
                f"greedy+slices[{sz}x{sd}]", findings,
                "kubernetes_tpu/ops/assign.py",
            )
            for f in ("frag_score", "carveouts", "contiguous_gangs",
                      "carveout_fallbacks"):
                if getattr(res, f, None) is None:
                    findings.append(
                        Finding(
                            CHECK, file, 1, f,
                            f"slice-family solve returned no {f} at "
                            f"bucket {sz}x{sd}",
                        )
                    )
            # fragmentation kernel vs SliceStats contracts
            try:
                stats = jax.eval_shape(
                    lambda c, sz=sz, sd=sd: slices_ops.fragmentation(
                        c, sz, sd
                    ),
                    snap.cluster,
                )
            except Exception as e:  # noqa: BLE001
                findings.append(
                    Finding(
                        CHECK, file, 1, "fragmentation",
                        f"eval_shape failed at slice bucket {sz}x{sd}: {e}",
                    )
                )
                continue
            senv = {"S": sz}
            for f in slices_ops.SliceStats._fields:
                c = stats_fields.get(f)
                val = getattr(stats, f)
                if c is None:
                    continue
                want = c.shape(senv)
                if tuple(val.shape) != want or str(val.dtype) != c.dtype:
                    findings.append(
                        Finding(
                            CHECK, file, c.line, f"SliceStats.{f}",
                            f"slices[{sz}x{sd}]: eval_shape output "
                            f"{val.dtype}{tuple(val.shape)} != contract "
                            f"{c.render()} (= {c.dtype}{want})",
                        )
                    )
    want_sigs = 2 * len(SLICE_LATTICE)
    if len(sigs) != want_sigs:
        findings.append(
            Finding(
                CHECK, file, 1, "carveout_eval",
                f"{want_sigs} slice lattice points produced {len(sigs)} "
                "distinct compile keys — slice_z/slice_dim/slice_require "
                "must each be part of the key",
            )
        )
    # sharded twin: the mesh shape must discriminate slice keys too
    ndev = len(jax.devices())
    size = 1
    while size * 2 <= min(ndev, 8):
        size *= 2
    mesh = sharded.make_mesh(size)
    mesh_sig = sharded.mesh_signature(mesh)
    ff = assign.FeatureFlags(slices=True, slice_z=2, slice_dim=2)
    if retrace.signature(snap, (1, ff, 4, mesh_sig)) == retrace.signature(
        snap, (1, ff, 4)
    ):
        findings.append(
            Finding(
                CHECK, file, 1, "carveout_eval",
                "sharded slice compile key collides with the single-chip "
                "key (mesh shape must be part of the signature)",
            )
        )
    if n % size == 0:
        try:
            res = jax.eval_shape(
                lambda s: sharded.sharded_greedy_assign(
                    s, mesh, topo_z=1, features=ff, n_groups=4
                ),
                snap,
            )
            if getattr(res, "frag_score", None) is None:
                findings.append(
                    Finding(
                        CHECK, file, 1, "frag_score",
                        "sharded slice-family solve returned no frag_score",
                    )
                )
        except Exception as e:  # noqa: BLE001
            findings.append(
                Finding(
                    CHECK, file, 1, "sharded_greedy_assign",
                    f"sharded slice eval_shape failed: {e}",
                )
            )


#: (node bucket, slot-capacity bucket, dirty-row bucket, insert bucket,
#: batch-class bucket) lattice the incremental-solve partials kernels
#: are driven across (ops/partials.py; models/partials.py pads every
#: index bucket with pad_dim)
PARTIALS_LATTICE: Tuple[Tuple[int, int, int, int, int], ...] = (
    (8, 32, 8, 1, 1), (16, 32, 8, 2, 2), (16, 64, 16, 2, 4),
)


def _check_partials_kernels(byclass, findings: List[Finding]) -> None:
    """Drive the incremental-solve partials kernels (ops/partials.py)
    through eval_shape across PARTIALS_LATTICE: outputs must match the
    ClassSpecs/PartialsStore/ClassStatics contracts at every bucket,
    the abstract signature set must be exactly one per lattice point,
    and the WARM solver twin must (a) eval_shape to the same SolveResult
    contracts as the cold one and (b) carry a compile key distinct from
    it — warm and cold are different executables by construction (the
    statics operands are part of the signature), single-chip and
    sharded alike."""
    import jax
    import numpy as np

    from ..ops import assign, partials as pops, schema
    from ..parallel import sharded
    from . import retrace

    file = "kubernetes_tpu/ops/partials.py"
    limits = schema.SnapshotLimits()
    spec_fields = byclass.get("ClassSpecs", {})
    store_fields = byclass.get("PartialsStore", {})
    statics_fields = byclass.get("ClassStatics", {})
    if not spec_fields or not store_fields or not statics_fields:
        findings.append(
            Finding(
                CHECK, file, 1, "ClassSpecs",
                "partials contracts missing (run the tensor-contract "
                "pass first)",
            )
        )
        return

    def env_for(n, g, d, m, c):
        return {
            "N": n, "G": g, "D": d, "M": m, "C": c,
            "T": limits.max_terms, "E": limits.max_exprs,
            "K": limits.max_ids_per_expr, "MT": limits.max_preferred,
            "TW": limits.taint_words, "PW": limits.port_words,
        }

    def abstract(cls, cfields, env):
        vals = {}
        for f in cls._fields:
            contract = cfields.get(f)
            if contract is None:
                raise KeyError(f"{cls.__name__}.{f} has no contract")
            vals[f] = jax.ShapeDtypeStruct(
                contract.shape(env), np.dtype(contract.dtype)
            )
        return cls(**vals)

    def check_out(result, cls_name, cfields, env, where):
        for f in type(result)._fields:
            contract = cfields.get(f)
            val = getattr(result, f)
            if contract is None:
                continue
            want = contract.shape(env)
            if tuple(val.shape) != want or str(val.dtype) != contract.dtype:
                findings.append(
                    Finding(
                        CHECK, file, contract.line, f"{cls_name}.{f}",
                        f"{where}: eval_shape output {val.dtype}"
                        f"{tuple(val.shape)} != contract "
                        f"{contract.render()} (= {contract.dtype}{want})",
                    )
                )

    signatures = {"eval": set(), "refresh": set(), "insert": set(),
                  "gather": set()}
    for n, g, d, m, c in PARTIALS_LATTICE:
        env = env_for(n, g, d, m, c)
        snap = abstract_snapshot(byclass, limits, n=n, p=8)
        cluster = snap.cluster
        specs = abstract(pops.ClassSpecs, spec_fields, env)
        store = abstract(
            pops.PartialsStore, store_fields, {"G": g, "N": n}
        )
        didx = jax.ShapeDtypeStruct((d,), "int32")
        midx = jax.ShapeDtypeStruct((m,), "int32")
        slots = jax.ShapeDtypeStruct((c,), "int32")
        try:
            out = jax.eval_shape(pops.eval_store, cluster, specs)
            check_out(
                out, "PartialsStore", store_fields, {"G": g, "N": n},
                f"eval_store[{n}x{g}]",
            )
            signatures["eval"].add(retrace.signature((cluster, specs)))
            out = jax.eval_shape(
                pops.refresh_rows, store, specs, cluster, didx
            )
            check_out(
                out, "PartialsStore", store_fields, {"G": g, "N": n},
                f"refresh_rows[{n}x{g}x{d}]",
            )
            signatures["refresh"].add(
                retrace.signature((store, specs, cluster, didx))
            )
            out = jax.eval_shape(
                pops.insert_slots, store, specs, cluster, midx
            )
            check_out(
                out, "PartialsStore", store_fields, {"G": g, "N": n},
                f"insert_slots[{n}x{g}x{m}]",
            )
            signatures["insert"].add(
                retrace.signature((store, specs, cluster, midx))
            )
            out = jax.eval_shape(pops.gather_statics, store, slots)
            check_out(
                out, "ClassStatics", statics_fields, {"C": c, "N": n},
                f"gather_statics[{n}x{g}x{c}]",
            )
            signatures["gather"].add(retrace.signature((store, slots)))
        except Exception as e:  # noqa: BLE001 — abstract eval failed
            findings.append(
                Finding(
                    CHECK, file, 1, "partials",
                    f"eval_shape failed at bucket "
                    f"{(n, g, d, m, c)}: {e}",
                )
            )
    for label, sigs in signatures.items():
        if len(sigs) != len(PARTIALS_LATTICE):
            findings.append(
                Finding(
                    CHECK, file, 1, label,
                    f"{len(PARTIALS_LATTICE)} lattice points produced "
                    f"{len(sigs)} distinct compile keys — the abstract "
                    "signature set must be exactly the bucket set",
                )
            )

    # WARM vs COLD solver twins: same SolveResult contracts, DISTINCT
    # compile keys (single-chip and sharded — the statics operands and
    # the mesh shape are both part of the signature)
    n, p, c = 16, 8, 2
    ff_off = assign.FeatureFlags()
    snap = abstract_snapshot(byclass, limits, n=n, p=p)
    statics = abstract(
        pops.ClassStatics, statics_fields, {"C": c, "N": n}
    )
    cold_sig = retrace.signature(snap, (1, ff_off, 0))
    warm_sig = retrace.signature((snap, statics), (1, ff_off, 0))
    if warm_sig == cold_sig:
        findings.append(
            Finding(
                CHECK, file, 1, "ClassStatics",
                "warm compile key collides with the cold key (the "
                "statics operands must be part of the signature)",
            )
        )
    try:
        res = jax.eval_shape(
            lambda s, st: assign.greedy_assign(
                s, topo_z=1, features=ff_off, n_groups=0, statics=st
            ),
            snap, statics,
        )
        _result_contract_check(
            res, "SolveResult", byclass,
            _class_env("ClusterTensors", limits, n, p, {}),
            f"greedy-warm[{n}x{p}]", findings,
            "kubernetes_tpu/ops/assign.py",
        )
    except Exception as e:  # noqa: BLE001
        findings.append(
            Finding(
                CHECK, file, 1, "greedy_assign",
                f"warm eval_shape failed at bucket {n}x{p}: {e}",
            )
        )
    ndev = len(jax.devices())
    size = 1
    while size * 2 <= min(ndev, 8):
        size *= 2
    mesh = sharded.make_mesh(size)
    mesh_sig = sharded.mesh_signature(mesh)
    if retrace.signature(
        (snap, statics), (1, ff_off, 0, mesh_sig)
    ) == warm_sig:
        findings.append(
            Finding(
                CHECK, file, 1, "ClassStatics",
                "sharded warm compile key collides with the single-chip "
                "warm key (mesh shape must be part of the signature)",
            )
        )
    if n % size == 0:
        try:
            res = jax.eval_shape(
                lambda s, st: sharded.sharded_greedy_assign(
                    s, mesh, topo_z=1, features=ff_off, n_groups=0,
                    statics=st,
                ),
                snap, statics,
            )
            _result_contract_check(
                res, "SolveResult", byclass,
                _class_env("ClusterTensors", limits, n, p, {}),
                f"greedy-sharded-warm[{n}x{p}]", findings,
                "kubernetes_tpu/parallel/sharded.py",
            )
        except Exception as e:  # noqa: BLE001
            findings.append(
                Finding(
                    CHECK, file, 1, "sharded_greedy_assign",
                    f"sharded warm eval_shape failed: {e}",
                )
            )


def _check_axis_transitions(byclass, findings: List[Finding]) -> None:
    """Elastic node axis (ISSUE 15): drive a REAL ClusterState through
    growth and shrink across pad buckets and prove the compile-key
    story end to end:

      * every exposed bucket is a pad bucket and growth is eager
        (monotone while adding);
      * WITHIN-bucket growth — more rows in the same bucket, or a
        backing-array realloc — provably reuses the existing keys (the
        exposed shapes are identical) and never bumps the struct
        generation;
      * each bucket CROSSING yields exactly one new compile key per
        kernel family — greedy cold, greedy WARM (partials statics) and
        the SHARDED twin included — i.e. the abstract-signature set
        equals the observed-bucket set for every family;
      * the lattice is closed under node-axis growth AND shrink: the
        post-dwell shrink lands exactly on a previously observed
        bucket, so the shrink re-uses an existing key instead of
        minting one (and the dwell pins the bucket until it is
        served)."""
    import jax
    import numpy as np

    from ..api import types as api
    from ..ops import assign, partials as pops, schema
    from ..parallel import sharded
    from ..utils import vocab as vbu
    from . import retrace

    file = "kubernetes_tpu/ops/schema.py"
    limits = schema.SnapshotLimits()
    state = schema.ClusterState(schema.SnapshotBuilder(limits))
    dwell = 3
    state.configure_elastic_axis(shrink_dwell=dwell)
    start = vbu.pad_dim(0, limits.min_nodes)

    def mk_node(i):
        node = api.Node(meta=api.ObjectMeta(name=f"ax-{i}", namespace=""))
        node.meta.labels[api.LABEL_HOSTNAME] = f"ax-{i}"
        node.status.allocatable = {
            api.CPU: 1000, api.MEMORY: 1 << 20, api.PODS: 16,
        }
        node.status.capacity = dict(node.status.allocatable)
        return node

    # -- growth walk: eager, pad-bucketed, shape-stable within a bucket --
    struct0 = state.struct_generation
    buckets: List[int] = []
    prev_shapes = None
    total = 4 * start + 1  # two crossings past the floor bucket
    for i in range(total):
        state.add_node(mk_node(i))
        t = state.tensors()
        n = int(t.allocatable.shape[0])
        shapes = tuple(np.shape(leaf) for leaf in t)
        if not vbu.is_pad_bucket(n, 1):
            findings.append(
                Finding(
                    CHECK, file, 1, "ClusterState.tensors",
                    f"exposed node axis {n} at {i + 1} nodes is not a "
                    "pad bucket",
                )
            )
            return
        if buckets and n < buckets[-1]:
            findings.append(
                Finding(
                    CHECK, file, 1, "ClusterState.tensors",
                    f"bucket shrank {buckets[-1]} -> {n} while ADDING "
                    "nodes (growth must be eager)",
                )
            )
        if buckets and n == buckets[-1] and shapes != prev_shapes:
            findings.append(
                Finding(
                    CHECK, file, 1, "ClusterState.tensors",
                    f"within-bucket add at {i + 1} nodes changed the "
                    "exposed shapes — the existing compile keys must be "
                    "reused",
                )
            )
        if not buckets or n != buckets[-1]:
            buckets.append(n)
        prev_shapes = shapes
    if state.struct_generation != struct0:
        findings.append(
            Finding(
                CHECK, file, 1, "ClusterState._grow",
                "node-axis growth bumped the struct generation — "
                "row-preserving reallocs must not force full resyncs",
            )
        )
    if len(buckets) < 3:
        findings.append(
            Finding(
                CHECK, file, 1, "ClusterState.tensors",
                f"growth walk observed buckets {buckets}; expected at "
                "least two crossings",
            )
        )
        return

    # -- within-bucket backing realloc: shapes and struct gen both hold --
    shapes0 = tuple(np.shape(leaf) for leaf in state.tensors())
    g0 = state.struct_generation
    state._grow(state._cap * 2)
    if state.struct_generation != g0:
        findings.append(
            Finding(
                CHECK, file, 1, "ClusterState._grow",
                "explicit backing-array grow bumped the struct "
                "generation",
            )
        )
    if tuple(np.shape(leaf) for leaf in state.tensors()) != shapes0:
        findings.append(
            Finding(
                CHECK, file, 1, "ClusterState._grow",
                "backing-array grow changed the exposed shapes without "
                "a bucket crossing",
            )
        )

    # -- one compile key per kernel family per observed bucket -----------
    p = 8
    ff_off = assign.FeatureFlags()
    spec_fields = byclass.get("ClassStatics", {})
    ndev = len(jax.devices())
    size = 1
    while size * 2 <= min(ndev, 8):
        size *= 2
    mesh = sharded.make_mesh(size)
    mesh_sig = sharded.mesh_signature(mesh)
    sigs = {"greedy": set(), "greedy-warm": set(), "greedy-sharded": set()}
    for n in buckets:
        snap = abstract_snapshot(byclass, limits, n=n, p=p)
        sigs["greedy"].add(retrace.signature(snap, (1, ff_off, 0)))
        if spec_fields:
            statics = pops.ClassStatics(
                **{
                    f: jax.ShapeDtypeStruct(
                        spec_fields[f].shape({"C": 2, "N": n}),
                        np.dtype(spec_fields[f].dtype),
                    )
                    for f in pops.ClassStatics._fields
                }
            )
            sigs["greedy-warm"].add(
                retrace.signature((snap, statics), (1, ff_off, 0))
            )
        sigs["greedy-sharded"].add(
            retrace.signature(snap, (1, ff_off, 0, mesh_sig))
        )
        try:
            res = jax.eval_shape(
                lambda s: assign.greedy_assign(
                    s, topo_z=1, features=ff_off, n_groups=0
                ),
                snap,
            )
            _result_contract_check(
                res, "SolveResult", byclass,
                _class_env("ClusterTensors", limits, n, p, {}),
                f"greedy-axis[{n}x{p}]", findings,
                "kubernetes_tpu/ops/assign.py",
            )
        except Exception as e:  # noqa: BLE001 — abstract eval failed
            findings.append(
                Finding(
                    CHECK, file, 1, "greedy_assign",
                    f"eval_shape failed at grown bucket {n}: {e}",
                )
            )
    for fam, got in sigs.items():
        if fam == "greedy-warm" and not spec_fields:
            continue
        if len(got) != len(buckets):
            findings.append(
                Finding(
                    CHECK, file, 1, fam,
                    f"{len(buckets)} observed buckets produced "
                    f"{len(got)} {fam} compile keys — a bucket crossing "
                    "must mint exactly one new key per kernel family",
                )
            )

    # -- shrink: dwell pins the bucket, then lands on a KNOWN bucket -----
    peak = buckets[-1]
    for i in range(total - start):
        state.remove_node(f"ax-{i}")
    for k in range(dwell + 1):
        # one generation per tick (the dwell counts generations, not
        # tensors() calls)
        state.add_node(mk_node(10_000 + k))
        state.remove_node(f"ax-{10_000 + k}")
        t = state.tensors()
        n = int(t.allocatable.shape[0])
        if k < dwell - 1 and n != peak:
            findings.append(
                Finding(
                    CHECK, file, 1, "ClusterState.tensors",
                    f"bucket moved to {n} after only {k + 1} "
                    f"below-bucket generation(s); the dwell is {dwell}",
                )
            )
    final = int(state.tensors().allocatable.shape[0])
    if final == peak:
        findings.append(
            Finding(
                CHECK, file, 1, "ClusterState.tensors",
                f"post-dwell shrink never served: bucket still {peak}",
            )
        )
    elif final not in buckets:
        findings.append(
            Finding(
                CHECK, file, 1, "ClusterState.tensors",
                f"shrink landed on {final}, never observed during "
                f"growth ({buckets}) — shrink must REUSE an existing "
                "compile key (lattice closure)",
            )
        )


def _check_gang_retry_closure(findings: List[Finding]) -> None:
    """The gang-admission binary search re-solves SUBSETS of the batch
    with num_pods_hint pinned to the full batch size: every subset must
    land in the full batch's pad bucket (one executable for the whole
    search, not one per subset size)."""
    from ..ops import schema
    from ..utils import vocab as vb

    min_pods = schema.SnapshotLimits().min_pods
    for full in GANG_RETRY_SIZES:
        bucket = vb.pad_dim(full, min_pods)
        bad = [
            k for k in range(1, full + 1)
            if vb.pad_dim(max(k, full), min_pods) != bucket
        ]
        if bad:
            findings.append(
                Finding(
                    CHECK, "kubernetes_tpu/utils/vocab.py", 1, "pad_dim",
                    f"bucket lattice not closed under gang-retry subsets "
                    f"of a {full}-pod batch: sizes {bad[:5]} escape bucket "
                    f"{bucket}",
                )
            )


def _check_preemption_kernel(byclass, findings: List[Finding]) -> None:
    """Drive the batched preemption dry-run (ops/preemption.py
    batched_dry_run) through eval_shape across PREEMPT_LATTICE: outputs
    must match the BatchDryRunResult contracts at every bucket, the
    abstract signature set must be exactly one per lattice point, and
    the encoder's pad buckets must be closed over the raw (candidate,
    victim, level, pod) sizes a PostFilter pass produces."""
    import jax
    import numpy as np

    from ..ops import preemption as pre_ops
    from ..ops import schema
    from ..utils import vocab as vb
    from . import retrace

    file = "kubernetes_tpu/ops/preemption.py"
    r = len(schema.FIXED_RESOURCES)
    batch_fields = byclass.get("PreemptionBatch", {})
    result_fields = byclass.get("BatchDryRunResult", {})
    if not batch_fields or not result_fields:
        findings.append(
            Finding(
                CHECK, file, 1, "PreemptionBatch",
                "preemption batch contracts missing (run the "
                "tensor-contract pass first)",
            )
        )
        return

    def abstract_batch(env):
        vals = {}
        for f in pre_ops.PreemptionBatch._fields:
            c = batch_fields.get(f)
            if c is None:
                raise KeyError(f"PreemptionBatch.{f} has no contract")
            vals[f] = jax.ShapeDtypeStruct(c.shape(env), np.dtype(c.dtype))
        return pre_ops.PreemptionBatch(**vals)

    signatures = set()
    for n, k, l, p in PREEMPT_LATTICE:
        env = {"N": n, "K": k, "L": l, "P": p, "R": r}
        batch = abstract_batch(env)
        signatures.add(retrace.signature(batch))
        try:
            res = jax.eval_shape(pre_ops.batched_dry_run, batch)
        except Exception as e:  # noqa: BLE001 — abstract eval failed
            findings.append(
                Finding(
                    CHECK, file, 1, "batched_dry_run",
                    f"eval_shape failed at bucket {n}x{k}x{l}x{p}: {e}",
                )
            )
            continue
        for f in pre_ops.BatchDryRunResult._fields:
            c = result_fields.get(f)
            val = getattr(res, f)
            if c is None:
                continue
            want = c.shape(env)
            if tuple(val.shape) != want or str(val.dtype) != c.dtype:
                findings.append(
                    Finding(
                        CHECK, file, c.line, f"BatchDryRunResult.{f}",
                        f"preempt[{n}x{k}x{l}x{p}]: eval_shape output "
                        f"{val.dtype}{tuple(val.shape)} != contract "
                        f"{c.render()} (= {c.dtype}{want})",
                    )
                )
    if len(signatures) != len(PREEMPT_LATTICE):
        findings.append(
            Finding(
                CHECK, file, 1, "batched_dry_run",
                f"{len(PREEMPT_LATTICE)} lattice points produced "
                f"{len(signatures)} distinct compile keys — the abstract "
                "signature set must be exactly the bucket set",
            )
        )
    # closure: every raw (candidate, victim, level, pod) size a pass
    # can produce must pad onto the power-of-two lattice family
    for raw_n, raw_k, raw_l, raw_p in PREEMPT_RAW_SIZES:
        padded = (
            vb.pad_dim(raw_n, 8), vb.pad_dim(raw_k, 4),
            vb.pad_dim(raw_l, 1), vb.pad_dim(raw_p, 4),
        )
        if not all(vb.is_pad_bucket(d, 1) for d in padded):
            findings.append(
                Finding(
                    CHECK, file, 1, "PreemptionBatch",
                    f"raw preemption sizes {(raw_n, raw_k, raw_l, raw_p)} "
                    f"pad to {padded} — not closed over the "
                    "power-of-two bucket family",
                )
            )
    # the batched static-feasibility dispatch reuses the snapshot
    # contracts: one eval at the base lattice point proves the vmapped
    # kernel is shape-stable over contract-built components
    from ..ops import schema as _schema

    limits = _schema.SnapshotLimits()
    snap = abstract_snapshot(byclass, limits, n=8, p=8)
    try:
        out = jax.eval_shape(
            pre_ops.static_feasible_batch,
            snap.cluster, snap.pods, snap.selectors,
        )
        if tuple(out.shape) != (8, 8) or str(out.dtype) != "bool":
            findings.append(
                Finding(
                    CHECK, file, 1, "static_feasible_batch",
                    f"static mask eval_shape produced {out.dtype}"
                    f"{tuple(out.shape)}, want bool[P, N]",
                )
            )
    except Exception as e:  # noqa: BLE001
        findings.append(
            Finding(
                CHECK, file, 1, "static_feasible_batch",
                f"eval_shape failed: {e}",
            )
        )


def _check_mesh_kernels(byclass, findings: List[Finding]) -> None:
    """Mesh-sharded solver twins driven through eval_shape across the
    lattice: outputs must match the result contracts at every bucket,
    the abstract signature set must be exactly one per (bucket, mesh
    shape) — the mesh shape IS part of the executable key — and every
    lattice node bucket must split evenly across the mesh (buckets and
    mesh sizes are both powers of two; smaller-than-mesh buckets are
    the counted single-chip fallback, not a compile surface).

    The mesh uses the largest power-of-two device count available
    (capped at 8): under the forced-host-platform test/bench
    environment that is a real 8-way mesh; a bare 1-device run still
    exercises the shard_map signatures."""
    import jax

    from ..ops import assign, schema
    from ..parallel import sharded
    from . import retrace

    ndev = len(jax.devices())
    size = 1
    while size * 2 <= min(ndev, 8):
        size *= 2
    mesh = sharded.make_mesh(size)
    mesh_sig = sharded.mesh_signature(mesh)
    file = "kubernetes_tpu/parallel/sharded.py"

    limits = schema.SnapshotLimits()
    ff_off = assign.FeatureFlags()

    def env_for(n, p):
        return _class_env("ClusterTensors", limits, n, p, {})

    signatures = {
        "greedy-sharded": set(), "wavefront-sharded": set(),
        "auction-sharded": set(),
    }
    calls = {"greedy-sharded": 0, "wavefront-sharded": 0,
             "auction-sharded": 0}
    from ..utils.vocab import pad_dim

    for n, p in LATTICE:
        if n % size:
            findings.append(
                Finding(
                    CHECK, file, 1, "make_mesh",
                    f"lattice node bucket {n} does not split across the "
                    f"{size}-device mesh — pad buckets and mesh sizes "
                    "must share the power-of-two family",
                )
            )
            continue
        snap = abstract_snapshot(byclass, limits, n=n, p=p)

        calls["greedy-sharded"] += 1
        signatures["greedy-sharded"].add(
            retrace.signature(snap, (1, ff_off, 0, mesh_sig))
        )
        try:
            res = jax.eval_shape(
                lambda s: sharded.sharded_greedy_assign(
                    s, mesh, topo_z=1, features=ff_off, n_groups=0
                ),
                snap,
            )
            _result_contract_check(
                res, "SolveResult", byclass, env_for(n, p),
                f"greedy-sharded[{n}x{p}]", findings, file,
            )
        except Exception as e:  # noqa: BLE001 — abstract eval failed
            findings.append(
                Finding(
                    CHECK, file, 1, "sharded_greedy_assign",
                    f"eval_shape failed at bucket {n}x{p}: {e}",
                )
            )

        w_pad = pad_dim(max(-(-p // assign.DEFAULT_WAVE_CAP), 1), 8)
        members = jax.ShapeDtypeStruct(
            (w_pad, assign.DEFAULT_WAVE_CAP), "int32"
        )
        calls["wavefront-sharded"] += 1
        signatures["wavefront-sharded"].add(
            retrace.signature((snap, members), (1, ff_off, 0, mesh_sig))
        )
        try:
            res = jax.eval_shape(
                lambda s, m: sharded.sharded_wavefront_assign(
                    s, m, mesh, topo_z=1, features=ff_off, n_groups=0
                ),
                snap, members,
            )
            _result_contract_check(
                res, "SolveResult", byclass, env_for(n, p),
                f"wavefront-sharded[{n}x{p}]", findings, file,
            )
        except Exception as e:  # noqa: BLE001
            findings.append(
                Finding(
                    CHECK, file, 1, "sharded_wavefront_assign",
                    f"eval_shape failed at bucket {n}x{p}: {e}",
                )
            )

        tie_k = min(64, n)
        calls["auction-sharded"] += 1
        signatures["auction-sharded"].add(
            retrace.signature(snap, (0, ff_off, (1, 1), tie_k, mesh_sig))
        )
        try:
            res = jax.eval_shape(
                lambda s: sharded.sharded_auction_assign(
                    s, mesh, n_groups=0, features=ff_off, topo_z=(1, 1),
                    tie_k=tie_k,
                ),
                snap,
            )
            _result_contract_check(
                res, "AuctionResult", byclass, env_for(n, p),
                f"auction-sharded[{n}x{p}]", findings, file,
            )
        except Exception as e:  # noqa: BLE001
            findings.append(
                Finding(
                    CHECK, file, 1, "sharded_auction_assign",
                    f"eval_shape failed at bucket {n}x{p}: {e}",
                )
            )

    for label, sigs in signatures.items():
        if len(sigs) != calls[label]:
            findings.append(
                Finding(
                    CHECK, file, 1, label,
                    f"{calls[label]} lattice points produced "
                    f"{len(sigs)} distinct compile keys — the sharded "
                    "signature set must be exactly one per (bucket, "
                    "mesh shape)",
                )
            )

    # the mesh shape must DISCRIMINATE: a sharded signature colliding
    # with its single-chip twin would let one executable cache serve
    # both layouts (prewarm/retrace keys carry the mesh for this reason)
    n, p = LATTICE[0]
    if n % size == 0:
        snap = abstract_snapshot(byclass, limits, n=n, p=p)
        if retrace.signature(snap, (1, ff_off, 0)) in signatures[
            "greedy-sharded"
        ]:
            findings.append(
                Finding(
                    CHECK, file, 1, "mesh_signature",
                    "sharded compile key collides with the single-chip "
                    "key (mesh shape must be part of the signature)",
                )
            )


def check(root: str, package: str = "kubernetes_tpu") -> List[Finding]:
    """Run the full recompile-discipline suite.  Imports JAX; callers
    wanting an import-light lint use run_all instead."""
    byclass = _schema_contracts(root, package)
    findings: List[Finding] = []
    _check_encode(byclass, findings)
    _check_kernels(byclass, findings)
    _check_preemption_kernel(byclass, findings)
    _check_mesh_kernels(byclass, findings)
    _check_slice_kernels(byclass, findings)
    _check_partials_kernels(byclass, findings)
    _check_axis_transitions(byclass, findings)
    _check_gang_retry_closure(findings)
    findings.sort(key=lambda f: (f.file, f.line, f.message))
    return findings
