"""graftsched scenario library — the control plane's real hot windows
driven under the deterministic interleaving explorer.

Each scenario builds REAL components (the sharded store, the scheduler
cache, the binding stage) inside an :class:`~.interleave.Explorer`
window, spawns the racing threads, drives the schedule to quiescence
and then asserts the pipeline's global invariants from a managed oracle
thread:

  * **rv monotonic / gapless** — every publish allocated exactly one
    resourceVersion; the global ring is 1..rv with no holes;
  * **watch replay == final store state** — an informer-style consumer
    (apply events, relist on Expired) converges to exactly the store's
    committed state, coalescing and expiry included;
  * **bound-exactly-once** — no pod ever carries two different nodes
    across any interleaving of commits, retries and fencing;
  * **per-shard sub-wave atomicity** — a fenced or failed sub-wave
    commits nothing; a committed one commits whole;
  * **assume set empty at quiesce** — every assume is confirmed,
    forgotten or expired by the time the pipeline drains;
  * **no lost pods** — every pod handed to the binding stage ends bound
    or back in the queue, across crash-grade binder faults.

Scenario classes keep heavyweight imports (api.store, the scheduler —
JAX) inside methods: this module is imported by the graftlint CLI for
``--interleave`` discovery, and the default import-light ``make lint``
path must never pull JAX.

Use :func:`run_schedule` for one seed and :func:`explore` for a sweep;
``python -m kubernetes_tpu.analysis --interleave`` and the
``interleave``-marked tests (make race) are the standard drivers.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Type

from ..testing import faults
from .interleave import Explorer

# -- oracle helpers ----------------------------------------------------------


def assert_rv_gapless(store, expected: int) -> None:
    """Every commit allocated exactly one rv; the global ring holds
    1..rv in order (monotonic AND gapless)."""
    assert store.resource_version == expected, (
        f"rv {store.resource_version} != {expected} commits"
    )
    rvs = [ev.rv for ev in store._buffer]
    assert rvs == sorted(rvs), f"ring not rv-monotonic: {rvs}"
    assert rvs == list(range(1, expected + 1)), (
        f"rv gap in ring: {rvs}"
    )


def store_pods(store) -> Dict[str, object]:
    items, _ = store.list("Pod")
    return {
        f"{p.meta.namespace}/{p.meta.name}": p for p in items
    }


class InformerConsumer:
    """Minimal informer: watch + apply + relist-on-Expired, the
    reflector contract reduced to its cache.  Runs inside a managed
    thread; `converge` loops until the cache equals `expected` (a
    schedule that loses events without an Expired signal never
    converges and fails the schedule budget — that IS the bug)."""

    def __init__(self, store, kind: str = "Pod"):
        self.store = store
        self.kind = kind
        self.cache: Dict[str, object] = {}
        self.relists = 0
        self._watch = None
        self._relist()

    def _key(self, obj) -> str:
        return f"{obj.meta.namespace}/{obj.meta.name}"

    def _relist(self) -> None:
        from ..api import store as st

        if self._watch is not None:
            self._watch.stop()
        items, rv = self.store.list(self.kind)
        self.cache = {self._key(o): o for o in items}
        self.relists += 1
        while True:
            try:
                self._watch = self.store.watch(self.kind, from_rv=rv)
                return
            except st.Expired:
                items, rv = self.store.list(self.kind)
                self.cache = {self._key(o): o for o in items}
                self.relists += 1

    def pump(self, timeout: float = 0.3) -> bool:
        """Apply one event; False on timeout.  Relists on expiry."""
        from ..api import store as st

        ev = self._watch.get(timeout=timeout)
        if ev is None:
            if self._watch.expired or self._watch.stopped:
                self._relist()
                return True
            return False
        if ev.type == st.DELETED:
            self.cache.pop(self._key(ev.obj), None)
        else:
            self.cache[self._key(ev.obj)] = ev.obj
        return True

    def converged(self, expected: Dict[str, int]) -> bool:
        """cache == expected as {key: resource_version}."""
        got = {
            k: o.meta.resource_version for k, o in self.cache.items()
        }
        return got == expected


# -- scenario protocol -------------------------------------------------------


class Scenario:
    """One reproducible hot window.  Subclasses implement setup()
    (build + spawn inside the explorer window), quiesced() (background
    drain predicate) and check() (invariant oracle, run as a managed
    thread)."""

    name = "scenario"

    @staticmethod
    def preload() -> None:
        """Import everything heavyweight BEFORE the explorer patches
        threading/time — a module import inside the window (lazy
        submodules, first-touch JAX) sees virtual primitives mid-
        initialization and breaks in baffling ways."""
        from ..api import store, types  # noqa: F401

    def fault_plan(self, reg: "faults.FaultRegistry") -> None:
        """Optional seeded fault schedules layered onto the run."""

    def setup(self, ex: Explorer) -> None:
        raise NotImplementedError

    def quiesced(self) -> bool:
        return True

    def check(self) -> None:
        raise NotImplementedError


def _store_quiesced(store) -> bool:
    return all(
        not s._dispatch_backlog and not s._dispatch_inflight
        for s in store._shards
    )


class WritersVsDispatch(Scenario):
    """Concurrent writers vs. the per-shard watch dispatcher vs.
    coalescing expiry: three writers churn two namespaces (different
    shards) on a sharded store while an informer-style consumer follows
    through a DELIBERATELY tiny coalescing buffer, so compaction,
    overflow-expiry and the relist path all run under every
    interleaving.  Oracles: rv monotonic/gapless, consumer cache ==
    final store state, zero destructive watcher terminations."""

    name = "writers_vs_dispatch"
    CAPACITY = 2        # per-watcher coalescing buffer: force expiry
    PODS_PER_NS = 3
    CHURN = True        # update + delete traffic on top of creates

    def setup(self, ex: Explorer) -> None:
        from ..api import store as st
        from ..api import types as api

        self.store = st.Store(shards=2, watch_capacity=self.CAPACITY)
        self.consumer = InformerConsumer(self.store)
        self.expected: Optional[Dict[str, int]] = None
        self.commits = 0
        self.writers_done = 0

        def writer(ns: str) -> None:
            for i in range(self.PODS_PER_NS):
                pod = api.Pod(
                    meta=api.ObjectMeta(name=f"p{i}", namespace=ns)
                )
                created = self.store.create(pod)
                self.commits += 1
                if self.CHURN:
                    created.status.phase = "Pending"
                    self.store.update(created)
                    self.commits += 1
                    if i == 0:
                        # one delete per namespace: annihilation coverage
                        self.store.delete("Pod", f"p{i}", ns)
                        self.commits += 1
            self.writers_done += 1

        def follow() -> None:
            # converge on the writers' final state; a schedule that
            # loses events without an Expired signal never converges
            # and fails the step budget loudly — that IS the bug shape
            while True:
                if self.writers_done == 2:
                    if self.expected is None:
                        self.expected = {
                            k: p.meta.resource_version
                            for k, p in store_pods(self.store).items()
                        }
                    if self.consumer.converged(self.expected):
                        return
                self.consumer.pump()

        ex.spawn(writer, "ns-a", name="writer-a")
        ex.spawn(writer, "ns-b", name="writer-b")
        ex.spawn(follow, name="consumer")

    def quiesced(self) -> bool:
        return _store_quiesced(self.store)

    def check(self) -> None:
        assert_rv_gapless(self.store, self.commits)
        got = {
            k: o.meta.resource_version
            for k, o in self.consumer.cache.items()
        }
        assert got == self.expected, (
            f"consumer diverged after {self.consumer.relists} relists: "
            f"{got} != {self.expected}"
        )
        stats = self.store.watch_stats()
        assert stats["watchers_terminated"] == 0, stats


class WritersVsDispatchFaulted(WritersVsDispatch):
    """writers_vs_dispatch with a fail-grade fault on the offer path:
    the fan-out thread's delivery raises mid-batch.  The watcher must
    EXPIRE (bookmark + relist) — regression pin for the silent
    batch-drop the explorer surfaced in Store._fan_out (a poisoned
    offer starved every remaining watcher of the rest of the batch with
    no 410 signal, so consumer caches went stale forever)."""

    name = "writers_vs_dispatch_faulted"
    # a ROOMY buffer and create-only traffic ON PURPOSE: no capacity
    # expiry forces a relist and no later event for the same object
    # papers over the hole, so the ONLY recovery from the poisoned
    # delivery is the containment path expiring the watcher — pre-fix,
    # the dropped create was simply gone and no seed converged
    CAPACITY = 256
    CHURN = False

    def fault_plan(self, reg: "faults.FaultRegistry") -> None:
        reg.fail("watch.offer", n=1)


class SubwaveVsFencing(Scenario):
    """Concurrent sub-wave commits vs. mid-wave leader fencing: leader
    A commits a fenced bind wave spanning both shards while a rival
    transfers the Lease.  Depending on where the transfer lands, A's
    wave commits whole, commits one shard's sub-wave, or commits
    nothing — but each sub-wave is all-or-nothing, nothing is ever
    bound twice, and a rejected sub-wave is counted in
    fenced_writes_total."""

    name = "subwave_vs_fencing"

    def setup(self, ex: Explorer) -> None:
        from ..api import store as st
        from ..api import types as api

        self.store = st.Store(shards=2)
        # two namespaces living on DIFFERENT shards → two sub-waves
        names = ["ns-a", "ns-b", "ns-c", "ns-d", "ns-e"]
        s0 = self.store.shard_index("Pod", names[0])
        self.ns_a = names[0]
        self.ns_b = next(
            n for n in names if self.store.shard_index("Pod", n) != s0
        )
        self.groups = {
            self.ns_a: [f"a{i}" for i in range(2)],
            self.ns_b: [f"b{i}" for i in range(2)],
        }
        for ns, pods in self.groups.items():
            for name in pods:
                self.store.create(
                    api.Pod(meta=api.ObjectMeta(name=name, namespace=ns))
                )
        lease = api.Lease(
            meta=api.ObjectMeta(name="scheduler", namespace="kube-system"),
            spec=api.LeaseSpec(holder_identity="A", lease_transitions=1),
        )
        self.store.create(lease)
        self.token = st.FenceToken(
            name="scheduler", namespace="kube-system",
            identity="A", generation=1,
        )
        self.fenced = False
        self.applied: List[str] = []

        def leader_commit() -> None:
            def mutate(pod) -> None:
                if pod.spec.node_name and pod.spec.node_name != "n1":
                    raise st.Conflict("double bind")
                pod.spec.node_name = "n1"

            updates = [
                (name, ns, mutate)
                for ns, pods in self.groups.items()
                for name in pods
            ]
            try:
                applied, errors = self.store.update_wave(
                    "Pod", updates, fence=self.token
                )
                self.applied = applied
                assert not errors, errors
            except st.Fenced:
                self.fenced = True

        def depose() -> None:
            cur = self.store.get("Lease", "scheduler", "kube-system")
            cur.spec.holder_identity = "B"
            cur.spec.lease_transitions = 2
            self.store.update(cur)

        ex.spawn(leader_commit, name="leader-A")
        ex.spawn(depose, name="rival-B")

    def quiesced(self) -> bool:
        return _store_quiesced(self.store)

    def check(self) -> None:
        pods = store_pods(self.store)
        by_shard_bound: Dict[str, List[bool]] = {}
        for ns, group in self.groups.items():
            bound = [
                pods[f"{ns}/{n}"].spec.node_name == "n1" for n in group
            ]
            assert pods  # keyed lookups above raise on lost pods
            for n in group:
                node = pods[f"{ns}/{n}"].spec.node_name
                assert node in (None, "", "n1"), (
                    f"bound to an impossible node: {node}"
                )
            # per-shard sub-wave atomicity: all-or-nothing per namespace
            assert all(bound) or not any(bound), (
                f"torn sub-wave in {ns}: {bound}"
            )
            by_shard_bound[ns] = bound
        if self.fenced:
            assert self.store.fenced_writes_total >= 1
            # the wave aborted at some sub-wave boundary: at least one
            # namespace must be wholly unbound
            assert not all(
                all(b) for b in by_shard_bound.values()
            ), "Fenced raised but every sub-wave committed"
        else:
            assert all(all(b) for b in by_shard_bound.values()), (
                f"no fence hit, but wave incomplete: {by_shard_bound}"
            )
        lease = self.store.get("Lease", "scheduler", "kube-system")
        assert lease.spec.holder_identity == "B"


class AssumeBridgeVsCommit(Scenario):
    """Assume-cache bridging vs. wave commit vs. TTL expiry: the
    scheduler cache assumes placements, the binder-side wave commits
    them through the store, the informer-side confirm races both, and a
    near-zero TTL cleanup sweep races everything.  Oracles: the assume
    set is EMPTY at quiesce (every assume confirmed or expired), every
    pod is bound exactly once in the store, and the cache accounts each
    bound pod exactly once (no phantom usage, no double accounting)."""

    name = "assume_bridge_vs_commit"
    PODS = 4

    @staticmethod
    def preload() -> None:
        from ..api import store, types  # noqa: F401
        from ..models.batch_scheduler import TPUBatchScheduler  # noqa: F401
        from ..scheduler.cache import SchedulerCache  # noqa: F401

    def setup(self, ex: Explorer) -> None:
        from ..api import store as st
        from ..api import types as api
        from ..models.batch_scheduler import TPUBatchScheduler
        from ..scheduler.cache import SchedulerCache

        self.store = st.Store(shards=2)
        tpu = TPUBatchScheduler()
        self.cache = SchedulerCache(tpu.state, ttl=0.001, clock=ex.clock)
        self.cache.add_node(
            api.Node(
                meta=api.ObjectMeta(name="n1", namespace=""),
                status=api.NodeStatus(
                    allocatable={"cpu": 64_000, "memory": 1 << 34, "pods": 110}
                ),
            )
        )
        self.pods = []
        for i in range(self.PODS):
            pod = api.Pod(meta=api.ObjectMeta(name=f"p{i}", namespace="d"))
            self.store.create(pod)
            self.pods.append(pod)
        self.requeued: List[object] = []
        self.confirm_done = False

        def assume_and_commit() -> None:
            for pod in self.pods:
                self.cache.assume(pod, "n1")

            def mutate(p) -> None:
                if p.spec.node_name and p.spec.node_name != "n1":
                    raise st.Conflict("double bind")
                p.spec.node_name = "n1"
                p.status.phase = "Running"

            applied, errors = self.store.update_wave(
                "Pod", [(p.meta.name, "d", mutate) for p in self.pods]
            )
            assert not errors, errors
            self.cache.finish_binding_all(self.pods)

        def confirm() -> None:
            # informer-side: follow the store and confirm binds in the
            # cache, exactly what Scheduler._on_pod does for bound pods
            # (from_rv=0: the commit may win the race to the ring, so
            # the bind events must REPLAY to a late registration)
            w = self.store.watch("Pod", from_rv=0)
            confirmed = set()
            while len(confirmed) < self.PODS:
                ev = w.get(timeout=0.3)
                if ev is None:
                    continue
                if ev.obj.spec.node_name:
                    self.cache.add_pod(ev.obj)
                    confirmed.add(ev.obj.meta.name)
            w.stop()
            self.confirm_done = True

        def expire_sweep() -> None:
            # the hot loop's cleanup_expired: TTL is ~0 in virtual time,
            # so any assume whose confirm lost the race gets expired and
            # requeued — the oracle proves the pipeline still converges
            for _ in range(6):
                self.requeued.extend(self.cache.cleanup_expired())

        ex.spawn(assume_and_commit, name="commit")
        ex.spawn(confirm, name="informer")
        ex.spawn(expire_sweep, name="expiry")

    def quiesced(self) -> bool:
        return _store_quiesced(self.store)

    def check(self) -> None:
        # every pod durably bound exactly once
        pods = store_pods(self.store)
        assert len(pods) == self.PODS
        for key, pod in pods.items():
            assert pod.spec.node_name == "n1", f"{key} lost its bind"
        # assume set empty: confirmed (informer) or expired (sweep)
        assert self.cache.assumed_count() == 0, (
            f"assume set not empty at quiesce: {self.cache.assumed_nodes()}"
        )
        # the cache accounts each pod at most once, and every pod it
        # does not account was expired (the requeue path owns it)
        accounted = sum(
            1 for p in self.pods if self.cache.state.has_pod(p)
        )
        expired_keys = {
            f"{p.meta.namespace}/{p.meta.name}" for p in self.requeued
        }
        assert accounted + len(expired_keys) >= self.PODS, (
            f"lost accounting: {accounted} accounted, "
            f"{len(expired_keys)} expired of {self.PODS}"
        )


class BinderCrashVsSalvage(Scenario):
    """Binder crash / restart vs. the salvage path: a staged bind wave
    meets a crash-grade fault inside the commit, the worker dies, the
    watchdog restarts it, and the retried wave must commit every pod
    EXACTLY once — while a concurrent mid-flight cycle dies and
    _salvage_cycle requeues its unhandled pods.  Oracles: no lost pods
    (bound or back in the queue), bound-exactly-once, wave backlog
    drained."""

    name = "binder_crash_vs_salvage"
    PODS = 3

    @staticmethod
    def preload() -> None:
        from ..api import store, types  # noqa: F401
        from ..scheduler import scheduler  # noqa: F401

    def fault_plan(self, reg: "faults.FaultRegistry") -> None:
        reg.crash("binder.commit_wave", n=1)

    def setup(self, ex: Explorer) -> None:
        from ..api import store as st
        from ..api import types as api
        from ..scheduler import scheduler as sched_mod
        from ..scheduler.queue import QueuedPodInfo, pod_key

        # 1-shard store: the commit pool (ThreadPoolExecutor +
        # SimpleQueue) would real-block inside the window
        self.store = st.Store(shards=1)
        self.sched = sched_mod.Scheduler(self.store, clock=ex.clock)
        self.cache = self.sched.cache
        self.cache.add_node(
            api.Node(
                meta=api.ObjectMeta(name="n1", namespace=""),
                status=api.NodeStatus(
                    allocatable={"cpu": 64_000, "memory": 1 << 34, "pods": 110}
                ),
            )
        )
        fwk = self.sched.profiles.default
        for i in range(self.PODS):
            pod = api.Pod(meta=api.ObjectMeta(name=f"p{i}", namespace="d"))
            self.store.create(pod)
            self.sched.queue.add(pod)
        # pop → assume → stage, exactly the _stage_group tail: the
        # queue's own infos ride the wave so failure paths requeue them
        batch = self.sched.queue.pop_batch(self.PODS, timeout=0)
        assert len(batch) == self.PODS
        wave = []
        for info in batch:
            self.cache.assume(info.pod, "n1")
            wave.append((fwk, info, "n1", ex.clock()))
        self.infos: List[QueuedPodInfo] = batch
        self.pod_key = pod_key

        def dispatch_and_flush() -> None:
            self.sched._dispatch_wave_async(wave)
            # flush_binds runs the binder watchdog each lap: the
            # crashed worker is restarted and the requeued remainder
            # commits on the second attempt
            assert self.sched.flush_binds(timeout=30.0)

        def salvage_racer() -> None:
            # a cycle that died mid-flight with nothing staged: its
            # popped pods must come back to the queue, not strand
            pod = api.Pod(meta=api.ObjectMeta(name="stray", namespace="d"))
            self.store.create(pod)
            self.sched.queue.add(pod)
            popped = self.sched.queue.pop_batch(1, timeout=0)
            assert len(popped) == 1
            cycle = sched_mod._Cycle({}, _NullTrace(), [], popped)
            self.sched._salvage_cycle(cycle)

        def stopper() -> None:
            # graceful stop from a MANAGED thread (joins are cooperative)
            self.sched.stop()

        ex.spawn(dispatch_and_flush, name="dispatch")
        ex.spawn(salvage_racer, name="salvage")
        self._stopper = stopper
        self._ex = ex

    def quiesced(self) -> bool:
        with self.sched._wave_cv:
            drained = not self.sched._waves and not self.sched._wave_active
        return drained and _store_quiesced(self.store)

    def check(self) -> None:
        pods = store_pods(self.store)
        for i in range(self.PODS):
            assert pods[f"d/p{i}"].spec.node_name == "n1", (
                f"pod p{i} lost its bind after the binder crash"
            )
        # the salvaged stray is unbound and back in the queue
        assert not pods["d/stray"].spec.node_name
        assert self.sched.queue.contains("d/stray"), (
            "salvage lost the popped pod"
        )
        assert self.sched.metrics.binder_restarts.total >= 1, (
            "binder crash never tripped the watchdog restart"
        )
        # committed pods left the queue; nothing stranded inflight
        stats = self.sched.queue.stats()
        assert stats["inflight"] == 0, stats
        self._stopper()


class _NullTrace:
    total = 0.0

    def step(self, *_a, **_k):
        pass

    def log_if_long(self):
        pass


class _StubElector:
    """Minimal leader elector for scenarios: always leading, fence
    tokens pinned to one acquisition (identity A, generation 1) — a
    rival transferring the Lease makes every later fenced commit
    reject, without the real elector's renew thread."""

    on_started_leading = None

    def __init__(self, token):
        self._token = token

    def is_leader(self) -> bool:
        return True

    def fence_token(self):
        return self._token


class SpeculativeSolveVsCommit(Scenario):
    """Lane A's SPECULATIVE solve over lane B's assumed placements vs.
    lane B's wave commit vs. assume-TTL expiry vs. a leader fence —
    the PR 12 speculative-overlap window.  Lane B assumes + stages a
    bind wave; lane A records the wave-failure generation, reads the
    snapshot lane B's assumes shaped (the encode analogue), and only
    stages its own wave when the speculation still holds — a commit
    failure or mid-wave fence (the rival's Lease transfer) must
    invalidate lane A's batch and requeue it whole.  Oracles:
    bound-exactly-once, no lost pod (bound or back in the queue),
    assume set empty at quiesce, rv ring gapless, a fenced wave
    commits nothing."""

    name = "speculative_solve_vs_commit"

    @staticmethod
    def preload() -> None:
        from ..api import store, types  # noqa: F401
        from ..scheduler import scheduler  # noqa: F401

    def setup(self, ex: Explorer) -> None:
        from ..api import store as st
        from ..api import types as api
        from ..scheduler import scheduler as sched_mod

        # 1-shard store: the commit pool (ThreadPoolExecutor) would
        # real-block inside the window (same constraint as
        # binder_crash_vs_salvage); streaming is exercised by the chaos
        # seeds with real threads instead
        self.store = st.Store(shards=1)
        lease = api.Lease(
            meta=api.ObjectMeta(name="scheduler", namespace="kube-system"),
            spec=api.LeaseSpec(holder_identity="A", lease_transitions=1),
        )
        self.store.create(lease)
        token = st.FenceToken(
            name="scheduler", namespace="kube-system",
            identity="A", generation=1,
        )
        self.sched = sched_mod.Scheduler(
            self.store, clock=ex.clock, assume_ttl=0.001,
            leader_elector=_StubElector(token),
        )
        self.cache = self.sched.cache
        self.cache.add_node(
            api.Node(
                meta=api.ObjectMeta(name="n1", namespace=""),
                status=api.NodeStatus(
                    allocatable={"cpu": 64_000, "memory": 1 << 34, "pods": 110}
                ),
            )
        )
        fwk = self.sched.profiles.default
        self.pods_b, self.pods_a = [], []
        for i in range(2):
            pod = api.Pod(meta=api.ObjectMeta(name=f"b{i}", namespace="d"))
            pod.spec.priority = 10
            self.store.create(pod)
            self.sched.queue.add(pod)
            self.pods_b.append(pod)
        for i in range(2):
            pod = api.Pod(meta=api.ObjectMeta(name=f"a{i}", namespace="d"))
            pod.spec.scheduler_name = "lane-a"
            self.store.create(pod)
            self.sched.queue.add(pod)
            self.pods_a.append(pod)
        self.invalidated = False
        self.a_observed_b_assumes = 0
        self.lanes_done = 0
        self.requeued: List[object] = []

        def lane_b() -> None:
            batch = self.sched.queue.pop_batch(
                2, timeout=0, profiles={"default-scheduler"}
            )
            assert len(batch) == 2, "lane B lost its pods"
            wave = []
            for info in batch:
                self.cache.assume(info.pod, "n1")
                wave.append((fwk, info, "n1", ex.clock()))
            self.sched._dispatch_wave_async(wave)
            self.lanes_done += 1

        def lane_a() -> None:
            # the speculative dispatch: record the wave-failure
            # generation, then "solve" over whatever lane B assumed
            token = self.sched._spec_token()
            with self.cache.lock:
                self.a_observed_b_assumes = sum(
                    1 for p in self.pods_b
                    if self.cache.state.has_pod(p)
                )
            batch = self.sched.queue.pop_batch(
                2, timeout=0, profiles={"lane-a"}
            )
            assert len(batch) == 2, "lane A lost its pods"
            if self.sched._spec_invalidated(token):
                # mis-speculation: requeue exactly this batch
                self.invalidated = True
                self.sched.metrics.misspeculation_total.inc()
                for info in batch:
                    self.sched.queue.requeue_backoff(info)
                self.lanes_done += 1
                return
            wave = []
            for info in batch:
                self.cache.assume(info.pod, "n1")
                wave.append((fwk, info, "n1", ex.clock()))
            self.sched._dispatch_wave_async(wave)
            self.lanes_done += 1

        def rival() -> None:
            cur = self.store.get("Lease", "scheduler", "kube-system")
            cur.spec.holder_identity = "B"
            cur.spec.lease_transitions = 2
            self.store.update(cur)

        def confirm_and_expire() -> None:
            # informer-style confirm + the assume-TTL sweep: loop until
            # every pod settled (bound-and-confirmed, or unbound and
            # back in the queue) so the assume set provably drains
            w = self.store.watch("Pod", from_rv=0)
            while not self._settled():
                ev = w.get(timeout=0.3)
                if ev is not None and ev.obj.spec.node_name:
                    self.cache.add_pod(ev.obj)
                for pod in self.cache.cleanup_expired():
                    self.requeued.append(pod)
                    self.sched.queue.add(pod)
            w.stop()

        ex.spawn(lane_b, name="lane-b")
        ex.spawn(lane_a, name="lane-a")
        ex.spawn(rival, name="rival")
        ex.spawn(confirm_and_expire, name="confirm")

    def _settled(self) -> bool:
        if self.lanes_done < 2 or self.sched._waves_in_flight():
            return False
        pods = store_pods(self.store)
        for pod in self.pods_b + self.pods_a:
            key = f"{pod.meta.namespace}/{pod.meta.name}"
            cur = pods.get(key)
            if cur is None:
                return False
            if cur.spec.node_name:
                if self.cache.is_assumed(cur):
                    return False  # confirm still pending
            elif not self.sched.queue.contains(key):
                return False  # neither bound nor requeued: in flight
        return True

    def quiesced(self) -> bool:
        with self.sched._wave_cv:
            drained = (
                not self.sched._waves
                and not self.sched._wave_active
                and not self.sched._stream_inflight
            )
        return drained and _store_quiesced(self.store)

    def check(self) -> None:
        pods = store_pods(self.store)
        fenced = self.store.fenced_writes_total
        bound_b = [
            bool(pods[f"d/{p.meta.name}"].spec.node_name)
            for p in self.pods_b
        ]
        for pod in self.pods_b + self.pods_a:
            cur = pods[f"d/{pod.meta.name}"]
            node = cur.spec.node_name
            assert node in (None, "", "n1"), (
                f"{pod.meta.name} bound to an impossible node: {node}"
            )
            if not node:
                # unbound at quiesce: must be back in the queue, never
                # stranded inflight or assumed
                key = f"d/{pod.meta.name}"
                assert self.sched.queue.contains(key), (
                    f"{key} lost: unbound and not requeued"
                )
        # a fenced wave commits nothing: fence hit => at least one
        # whole wave's pods stayed unbound
        if fenced:
            assert not all(bound_b) or not all(
                bool(pods[f"d/{p.meta.name}"].spec.node_name)
                for p in self.pods_a
            ), "Fenced raised but every wave committed"
        # mis-speculation accounting: lane A invalidated => its pods
        # requeued whole (none bound), and the failure generation moved
        if self.invalidated:
            assert self.sched._spec_token() >= 1
            for p in self.pods_a:
                assert not pods[f"d/{p.meta.name}"].spec.node_name, (
                    "invalidated speculative batch still bound a pod"
                )
        # assume set empty at quiesce (confirmed, expired, or released)
        assert self.cache.assumed_count() == 0, (
            f"assume set not empty: {self.cache.assumed_nodes()}"
        )
        # rv ring gapless and monotonic across every commit path
        rvs = [ev.rv for ev in self.store._buffer]
        assert rvs == list(
            range(1, self.store.resource_version + 1)
        ), f"rv ring not gapless: {rvs}"
        self.sched.stop()


SCENARIOS: Dict[str, Type[Scenario]] = {
    cls.name: cls
    for cls in (
        WritersVsDispatch,
        WritersVsDispatchFaulted,
        SubwaveVsFencing,
        AssumeBridgeVsCommit,
        BinderCrashVsSalvage,
        SpeculativeSolveVsCommit,
    )
}


# -- drivers -----------------------------------------------------------------


def run_schedule(
    scenario_cls: Type[Scenario],
    seed: int,
    policy: str = "random",
    max_steps: int = 50_000,
) -> Explorer:
    """One scenario under one schedule; returns the Explorer (trace,
    steps) on success, raises the failing oracle/deadlock otherwise."""
    import gc

    sc = scenario_cls()
    scenario_cls.preload()
    ex = Explorer(seed=seed, policy=policy, max_steps=max_steps)
    reg = faults.FaultRegistry(seed)
    sc.fault_plan(reg)
    with faults.armed(reg):
        with ex.installed():
            sc.setup(ex)
            ex.drive(quiesce=sc.quiesced)
            ex.run_inline(sc.check, name="oracle")
    # drop scenario refs so detached service loops exit via weakrefs
    del sc
    gc.collect()
    return ex


def explore(
    scenario_cls: Type[Scenario],
    seeds=range(100),
    policies=("random", "pct"),
    max_steps: int = 50_000,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict[str, int]:
    """Sweep a scenario across seeds × policies.  Every schedule must
    pass; returns {"schedules": n, "yield_points": n} for reporting."""
    schedules = 0
    points = 0
    for policy in policies:
        for seed in seeds:
            ex = run_schedule(
                scenario_cls, seed, policy=policy, max_steps=max_steps
            )
            schedules += 1
            points += ex.steps
            if progress is not None and schedules % 25 == 0:
                progress(
                    f"{scenario_cls.name}: {schedules} schedules, "
                    f"{points} yield points"
                )
    return {"schedules": schedules, "yield_points": points}
