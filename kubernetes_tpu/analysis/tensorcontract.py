"""tensor-contract: the dense-tensor schema is a checked contract.

The Filter/Score pipeline lives in statically-shaped arrays whose
dtype/axis conventions (ops/schema.py) used to be prose comments.  This
pass parses them into machine-readable contracts (analysis/contracts.py)
and enforces, over the ``ops/``, ``models/`` and ``parallel/`` packages:

  presence    every array field of every NamedTuple carries a parseable
              ``# <dtype>[<axes>]`` contract comment;
  dtype       kernel/host-prep code must stay dtype-stable: no 64-bit
              numpy dtypes (``np.float64`` host values weak-type-promote
              downstream f32 device math; ``np.int64`` widens i32/u32
              bitset state), no ``dtype=float`` / ``dtype=int`` /
              ``.astype(float)`` round-trips through Python's 64-bit
              builtins;
  bitset      ``u32`` bitset updates must wrap Python int shifts
              (``bits |= 1 << i`` silently widens the whole expression
              to i64; ``bits |= np.uint32(1 << i)`` does not);
  axes        a variable derived from one symbolic axis must not index
              an array along a different one: ``p = pods.req.shape[0]``
              binds ``p ≡ P``, so ``cluster.allocatable[:p]`` (axis 0 is
              ``N``) is flagged.  ``X.shape[k]`` beyond the declared
              rank is flagged too;
  boundary    device transfers of bare Python list/tuple literals
              (``jnp.asarray([..])`` promotes to 64-bit by default) must
              carry an explicit dtype — host/device crossings go through
              the schema dtypes.

Chain resolution is conservative: ``<...>.pods.req`` resolves through
the Snapshot composition (contracts.container_map), a bare field name
resolves only when exactly one NamedTuple in scope declares it, and
everything else is skipped.  Deliberate 64-bit host-only state (e.g.
ClusterState's generation counters, which never cross to the device)
carries a line suppression with a justification.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from . import Finding, SourceFile, dotted_name
from . import contracts as ct

CHECK = "tensor-contract"

#: packages (relative to the scanned package root) the pass spans
DEFAULT_SCOPE = ("ops", "models", "parallel")

SCHEMA_FILE = "ops/schema.py"

_WIDE_DTYPES = {"float64", "int64", "uint64", "double", "longlong"}
_NUMPY_ROOTS = {"np", "numpy", "jnp", "jax"}
_TRANSFER_FNS = {"asarray", "array", "device_put"}
_UINT_WRAPPERS = {"uint32", "uint16", "uint8", "int32"}
_BITWISE_OPS = (ast.BitOr, ast.BitAnd, ast.BitXor)


def _in_scope(relpath: str, package: str, scope: Tuple[str, ...]) -> bool:
    parts = relpath.replace("\\", "/").split("/")
    return len(parts) >= 2 and parts[0] == package and parts[1] in scope


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """['snap', 'pods', 'req'] for a pure Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


class _Resolver:
    """Attribute-chain -> Contract, via the Snapshot composition map or
    a globally-unique field name."""

    def __init__(self, contracts: Sequence[ct.Contract],
                 containers: Dict[str, str]):
        self.by_class = ct.index_by_class(contracts)
        self.containers = containers
        by_field: Dict[str, List[ct.Contract]] = {}
        for c in contracts:
            by_field.setdefault(c.field, []).append(c)
        self.unique = {
            f: cs[0] for f, cs in by_field.items() if len(cs) == 1
        }

    def resolve(self, node: ast.AST) -> Optional[ct.Contract]:
        chain = _attr_chain(node)
        if chain is None or len(chain) < 2:
            return None
        field = chain[-1]
        container = chain[-2]
        cls = self.containers.get(container)
        if cls is not None:
            return self.by_class.get(cls, {}).get(field)
        return self.unique.get(field)


def _index_elements(index: ast.AST) -> Optional[List[ast.AST]]:
    """Positional index elements, or None when the subscript uses
    Ellipsis/newaxis (axis positions no longer line up)."""
    elts = list(index.elts) if isinstance(index, ast.Tuple) else [index]
    for e in elts:
        if isinstance(e, ast.Constant) and e.value in (Ellipsis, None):
            return None
    return elts


def _names_in_index_elt(elt: ast.AST) -> List[str]:
    """Bare axis-variable names an index element compares against the
    declared axis: a plain name, or the lower/upper of a plain slice."""
    if isinstance(elt, ast.Name):
        return [elt.id]
    if isinstance(elt, ast.Slice):
        out = []
        for side in (elt.lower, elt.upper):
            if isinstance(side, ast.Name):
                out.append(side.id)
        return out
    return []


class _FunctionChecker(ast.NodeVisitor):
    """Axis-consistency walk of one function body."""

    def __init__(self, pass_, symbol: str):
        self.p = pass_
        self.symbol = symbol
        self.bindings: Dict[str, str] = {}  # var -> axis symbol

    # -- bindings ---------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        self._maybe_bind(node)
        self.generic_visit(node)

    def _maybe_bind(self, node: ast.Assign) -> None:
        if len(node.targets) != 1:
            return
        target, value = node.targets[0], node.value
        # v = <chain>.shape[k]
        if (
            isinstance(target, ast.Name)
            and isinstance(value, ast.Subscript)
            and isinstance(value.value, ast.Attribute)
            and value.value.attr == "shape"
            and isinstance(value.slice, ast.Constant)
            and isinstance(value.slice.value, int)
        ):
            contract = self.p.resolver.resolve(value.value.value)
            if contract is None:
                return
            k = value.slice.value
            if k >= contract.rank or k < -contract.rank:
                self.p.flag(
                    value.lineno, self.symbol,
                    f"shape[{k}] out of range for {contract.cls}."
                    f"{contract.field} {contract.render()} "
                    f"(rank {contract.rank})",
                )
                return
            axis = contract.axes[k]
            if axis.sym is not None and not axis.ceil:
                self.bindings[target.id] = axis.sym
            return
        # a, b = <chain>.shape
        if (
            isinstance(target, ast.Tuple)
            and isinstance(value, ast.Attribute)
            and value.attr == "shape"
        ):
            contract = self.p.resolver.resolve(value.value)
            if contract is None:
                return
            if any(isinstance(t, ast.Starred) for t in target.elts):
                return
            if len(target.elts) != contract.rank:
                self.p.flag(
                    value.lineno, self.symbol,
                    f"unpacks {len(target.elts)} dims from {contract.cls}."
                    f"{contract.field} {contract.render()} "
                    f"(rank {contract.rank})",
                )
                return
            for t, axis in zip(target.elts, contract.axes):
                if (
                    isinstance(t, ast.Name)
                    and axis.sym is not None
                    and not axis.ceil
                ):
                    self.bindings[t.id] = axis.sym

    # -- usage ------------------------------------------------------------

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # <chain>.shape[k] rank check (unassigned uses too)
        if (
            isinstance(node.value, ast.Attribute)
            and node.value.attr == "shape"
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, int)
        ):
            contract = self.p.resolver.resolve(node.value.value)
            if contract is not None:
                k = node.slice.value
                if k >= contract.rank or k < -contract.rank:
                    self.p.flag(
                        node.lineno, self.symbol,
                        f"shape[{k}] out of range for {contract.cls}."
                        f"{contract.field} {contract.render()} "
                        f"(rank {contract.rank})",
                    )
            self.generic_visit(node)
            return
        contract = self.p.resolver.resolve(node.value)
        if contract is not None:
            elts = _index_elements(node.slice)
            if elts is not None:
                for j, elt in enumerate(elts):
                    if j >= contract.rank:
                        self.p.flag(
                            node.lineno, self.symbol,
                            f"{contract.rank + 1}+ indices into "
                            f"{contract.cls}.{contract.field} "
                            f"{contract.render()} (rank {contract.rank})",
                        )
                        break
                    declared = contract.axes[j]
                    if declared.sym is None or declared.ceil:
                        continue
                    for name in _names_in_index_elt(elt):
                        used = self.bindings.get(name)
                        if used is not None and used != declared.sym:
                            self.p.flag(
                                node.lineno, self.symbol,
                                f"indexes {contract.cls}.{contract.field} "
                                f"axis {j} (declared {declared.sym}) with "
                                f"{used}-derived variable '{name}'",
                            )
        self.generic_visit(node)

    # nested defs get their own binding scope via the outer walk
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.p.check_function(node, f"{self.symbol}.{node.name}",
                              parent_bindings=self.bindings)

    visit_AsyncFunctionDef = visit_FunctionDef


class _FilePass:
    def __init__(self, src: SourceFile, resolver: _Resolver,
                 findings: List[Finding]):
        self.src = src
        self.resolver = resolver
        self.findings = findings

    def flag(self, line: int, symbol: str, message: str) -> None:
        if not self.src.suppressed(line, CHECK):
            self.findings.append(
                Finding(CHECK, self.src.relpath, line, symbol, message)
            )

    # -- per-function axis walk -------------------------------------------

    def check_function(self, node, symbol: str,
                       parent_bindings: Optional[Dict[str, str]] = None):
        checker = _FunctionChecker(self, symbol)
        if parent_bindings:
            checker.bindings.update(parent_bindings)
        for stmt in node.body:
            checker.visit(stmt)

    def check_axes(self) -> None:
        for node in self.src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.check_function(node, node.name)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.check_function(sub, f"{node.name}.{sub.name}")

    # -- dtype / bitset / boundary hazards --------------------------------

    def check_dtypes(self) -> None:
        seen = set()
        parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.src.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        symbol_of = self._symbol_index()
        for node in ast.walk(self.src.tree):
            line = getattr(node, "lineno", None)
            if line is None:
                continue
            symbol = symbol_of(line)
            # 64-bit numpy dtype mention anywhere in kernel scope
            if isinstance(node, ast.Attribute) and node.attr in _WIDE_DTYPES:
                root = _attr_chain(node)
                if root is not None and root[0] in _NUMPY_ROOTS:
                    key = (line, node.attr)
                    if key not in seen:
                        seen.add(key)
                        self.flag(
                            line, symbol,
                            f"64-bit dtype {'.'.join(root)} (weak-type "
                            "promotes f32/i32 schema state; use the "
                            "contract dtype)",
                        )
            if isinstance(node, ast.keyword) and node.arg == "dtype":
                v = node.value
                if isinstance(v, ast.Name) and v.id in ("float", "int"):
                    self.flag(
                        line, symbol,
                        f"dtype={v.id} resolves to 64-bit "
                        "(use the contract dtype)",
                    )
                elif (
                    isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                    and v.value in _WIDE_DTYPES
                ):
                    self.flag(
                        line, symbol,
                        f"dtype='{v.value}' (64-bit; use the contract dtype)",
                    )
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in ("float", "int")
            ):
                self.flag(
                    line, symbol,
                    f".astype({node.args[0].id}) round-trips through a "
                    "64-bit builtin (use the contract dtype)",
                )
            # u32 scalar shifted by an unwrapped arithmetic expression:
            # `np.uint32(1) << (i32 & 31)` promotes the WHOLE expression
            # to i64 under NumPy 2 value-independent promotion
            if (
                isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.LShift)
                and isinstance(node.left, ast.Call)
                and (dotted_name(node.left.func) or "").split(".")[-1]
                in _UINT_WRAPPERS
                and isinstance(node.right, (ast.BinOp, ast.Name))
            ):
                self.flag(
                    line, symbol,
                    "uint-wrapped scalar shifted by an unwrapped "
                    "expression promotes to i64 (NumPy 2); cast the "
                    "shift count with .astype(np.uint32)",
                )
            # u32 bitset math widened to i64 by a bare Python int shift
            if (
                isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.LShift)
                and isinstance(node.left, ast.Constant)
                and isinstance(node.left.value, int)
            ):
                cur, in_bitexpr, wrapped = node, False, False
                while cur in parents:
                    cur = parents[cur]
                    if isinstance(cur, ast.BinOp) and isinstance(
                        cur.op, _BITWISE_OPS
                    ):
                        in_bitexpr = True
                    elif isinstance(cur, ast.AugAssign) and isinstance(
                        cur.op, _BITWISE_OPS
                    ):
                        in_bitexpr = True
                    elif isinstance(cur, ast.Call):
                        name = dotted_name(cur.func)
                        if name is not None and name.split(".")[-1] in _UINT_WRAPPERS:
                            wrapped = True
                    elif isinstance(cur, (ast.FunctionDef, ast.ClassDef)):
                        break
                if in_bitexpr and not wrapped:
                    self.flag(
                        line, symbol,
                        "bare Python int shift in bitset math widens to "
                        "i64; wrap in np.uint32(...)",
                    )
            # host/device boundary: literal transfers without a dtype
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if (
                    name is not None
                    and name.split(".")[0] in ("jnp", "jax")
                    and name.split(".")[-1] in _TRANSFER_FNS
                    and node.args
                    and isinstance(node.args[0], (ast.List, ast.Tuple))
                    and not any(k.arg == "dtype" for k in node.keywords)
                ):
                    self.flag(
                        line, symbol,
                        f"{name} of a Python literal without dtype "
                        "(promotes to 64-bit; cross the boundary through "
                        "schema dtypes)",
                    )

    def _symbol_index(self):
        """line -> enclosing 'Class.method'/'function' name (best effort)."""
        spans: List[Tuple[int, int, str]] = []

        def add(node, name):
            end = getattr(node, "end_lineno", node.lineno)
            spans.append((node.lineno, end, name))

        for node in self.src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add(node, node.name)
            elif isinstance(node, ast.ClassDef):
                add(node, node.name)
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        add(sub, f"{node.name}.{sub.name}")
        spans.sort()

        def lookup(line: int) -> str:
            best = "<module>"
            for lo, hi, name in spans:
                if lo <= line <= hi:
                    best = name  # later (inner) spans refine
            return best

        return lookup


def check(
    files: List[SourceFile],
    package: str = "kubernetes_tpu",
    scope: Tuple[str, ...] = DEFAULT_SCOPE,
) -> List[Finding]:
    in_scope = [f for f in files if _in_scope(f.relpath, package, scope)]

    # contract presence + the shared contract table
    all_contracts: List[ct.Contract] = []
    containers: Dict[str, str] = {}
    findings: List[Finding] = []
    for src in in_scope:
        contracts, issues = ct.collect(src)
        all_contracts.extend(contracts)
        containers.update(ct.container_map(src))
        for issue in issues:
            if src.suppressed(issue.line, CHECK):
                continue
            findings.append(
                Finding(
                    CHECK, src.relpath, issue.line,
                    f"{issue.cls}.{issue.field}",
                    f"array field without a tensor contract ({issue.reason}); "
                    "annotate `# <dtype>[<axes>]`",
                )
            )

    resolver = _Resolver(all_contracts, containers)
    for src in in_scope:
        fp = _FilePass(src, resolver, findings)
        fp.check_dtypes()
        fp.check_axes()
    return findings
