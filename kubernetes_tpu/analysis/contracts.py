"""Tensor contracts: the machine-readable half of the dense schema.

Every ``np.ndarray`` / ``jnp.ndarray`` field of a NamedTuple in the ops
tree carries a trailing comment of the form::

    allocatable: np.ndarray        # f32[N, R]
    taint_bits: np.ndarray         # u32[3, N, TW]  effect-major
    matches_incoming: np.ndarray   # u32[P, ceil(T/32)] packed ...
    rounds: jnp.ndarray            # i32[]: bidding rounds executed

This module parses those comments into :class:`Contract` objects —
``(class, field, dtype, symbolic axes)`` — which are the single source
of truth two enforcement layers share:

  * the ``tensor-contract`` static pass (analysis/tensorcontract.py)
    fails on unannotated/unparseable fields and checks kernel code
    against the declared dtypes and axis symbols;
  * the ``recompile-discipline`` pass (analysis/shapes.py) resolves the
    symbolic axes against concrete pad-bucket environments to build
    abstract snapshots for ``jax.eval_shape`` and to validate that the
    real encoder lands exactly on the declared shapes.

Grammar (everything after the closing ``]`` is free prose)::

    contract := dtype '[' axes? ']'
    dtype    := 'bool' | [iuf] (8|16|32|64) | 'bf16'
    axes     := axis (',' axis)*
    axis     := INT | IDENT | 'ceil(' IDENT '/' INT ')'

Import-light on purpose (stdlib only): ``make lint`` parses contracts
without initializing JAX.
"""

from __future__ import annotations

import ast
import math
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from . import SourceFile

#: contract dtype token -> numpy dtype name
DTYPES = {
    "bool": "bool",
    "i8": "int8",
    "i16": "int16",
    "i32": "int32",
    "i64": "int64",
    "u8": "uint8",
    "u16": "uint16",
    "u32": "uint32",
    "u64": "uint64",
    "f16": "float16",
    "bf16": "bfloat16",
    "f32": "float32",
    "f64": "float64",
}

_SPEC_RE = re.compile(
    r"^(?P<dtype>bool|bf16|[iuf](?:8|16|32|64))\[(?P<axes>[^\]]*)\]"
)
_CEIL_RE = re.compile(r"^ceil\(\s*([A-Za-z_]\w*)\s*/\s*(\d+)\s*\)$")
_IDENT_RE = re.compile(r"^[A-Za-z_]\w*$")


@dataclass(frozen=True)
class Axis:
    """One axis of a contract: a literal size, a symbol, or ceil(sym/k)."""

    sym: Optional[str]   # None for a literal axis
    const: int = 0       # literal size, or the ceil divisor
    ceil: bool = False

    def resolve(self, env: Dict[str, int]) -> int:
        if self.sym is None:
            return self.const
        v = env[self.sym]
        return math.ceil(v / self.const) if self.ceil else v

    def render(self) -> str:
        if self.sym is None:
            return str(self.const)
        if self.ceil:
            return f"ceil({self.sym}/{self.const})"
        return self.sym


@dataclass(frozen=True)
class Contract:
    cls: str
    field: str
    dtype: str           # numpy dtype name ("int32", "bool", ...)
    axes: Tuple[Axis, ...]
    line: int            # 1-based line of the field in its source file
    file: str            # relpath of the defining source file

    @property
    def rank(self) -> int:
        return len(self.axes)

    def shape(self, env: Dict[str, int]) -> Tuple[int, ...]:
        return tuple(a.resolve(env) for a in self.axes)

    def render(self) -> str:
        short = {v: k for k, v in DTYPES.items()}[self.dtype]
        return f"{short}[{', '.join(a.render() for a in self.axes)}]"


def parse_spec(text: str) -> Optional[Tuple[str, Tuple[Axis, ...]]]:
    """Parse a comment body into (numpy dtype name, axes), or None."""
    m = _SPEC_RE.match(text.strip())
    if m is None:
        return None
    dtype = DTYPES[m.group("dtype")]
    axes: List[Axis] = []
    body = m.group("axes").strip()
    if body:
        for token in body.split(","):
            token = token.strip()
            if token.isdigit():
                axes.append(Axis(sym=None, const=int(token)))
                continue
            cm = _CEIL_RE.match(token)
            if cm is not None:
                axes.append(Axis(sym=cm.group(1), const=int(cm.group(2)), ceil=True))
                continue
            if _IDENT_RE.match(token):
                axes.append(Axis(sym=token))
                continue
            return None
    return dtype, tuple(axes)


_ARRAY_ANNOTATIONS = {"ndarray", "Array"}


def _is_array_annotation(node: ast.AST) -> bool:
    """np.ndarray / jnp.ndarray / numpy.ndarray / jax.Array."""
    while isinstance(node, ast.Attribute):
        if node.attr in _ARRAY_ANNOTATIONS:
            return True
        node = node.value
    return isinstance(node, ast.Name) and node.id in _ARRAY_ANNOTATIONS


def _is_namedtuple_class(node: ast.ClassDef) -> bool:
    for base in node.bases:
        name = base.attr if isinstance(base, ast.Attribute) else (
            base.id if isinstance(base, ast.Name) else None
        )
        if name == "NamedTuple":
            return True
    return False


@dataclass(frozen=True)
class ContractIssue:
    """A field that should carry a contract but doesn't parse."""

    cls: str
    field: str
    line: int
    reason: str  # "unannotated" | "unparseable: <comment>"


def collect(src: SourceFile) -> Tuple[List[Contract], List[ContractIssue]]:
    """Contracts (and presence/parse issues) for every array-annotated
    NamedTuple field in one module."""
    contracts: List[Contract] = []
    issues: List[ContractIssue] = []
    for node in src.tree.body:
        if not isinstance(node, ast.ClassDef) or not _is_namedtuple_class(node):
            continue
        for stmt in node.body:
            if not isinstance(stmt, ast.AnnAssign):
                continue
            if not isinstance(stmt.target, ast.Name):
                continue
            if not _is_array_annotation(stmt.annotation):
                continue
            field = stmt.target.id
            line = stmt.lineno
            text = src.lines[line - 1] if line <= len(src.lines) else ""
            _, hash_, comment = text.partition("#")
            if not hash_:
                issues.append(
                    ContractIssue(node.name, field, line, "unannotated")
                )
                continue
            spec = parse_spec(comment)
            if spec is None:
                issues.append(
                    ContractIssue(
                        node.name, field, line,
                        f"unparseable contract comment {comment.strip()!r}",
                    )
                )
                continue
            dtype, axes = spec
            contracts.append(
                Contract(node.name, field, dtype, axes, line, src.relpath)
            )
    return contracts, issues


def index_by_class(
    contracts: Sequence[Contract],
) -> Dict[str, Dict[str, Contract]]:
    out: Dict[str, Dict[str, Contract]] = {}
    for c in contracts:
        out.setdefault(c.cls, {})[c.field] = c
    return out


def container_map(src: SourceFile) -> Dict[str, str]:
    """Field-name -> class-name for NamedTuple fields annotated with
    OTHER NamedTuple classes (the Snapshot composition: ``pods:
    PodBatch`` makes ``<x>.pods.<field>`` resolvable to PodBatch's
    contract for ``<field>``)."""
    classes = {
        node.name
        for node in src.tree.body
        if isinstance(node, ast.ClassDef) and _is_namedtuple_class(node)
    }
    out: Dict[str, str] = {}
    for node in src.tree.body:
        if not isinstance(node, ast.ClassDef) or not _is_namedtuple_class(node):
            continue
        for stmt in node.body:
            if (
                isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
                and isinstance(stmt.annotation, ast.Name)
                and stmt.annotation.id in classes
            ):
                out[stmt.target.id] = stmt.annotation.id
    return out
