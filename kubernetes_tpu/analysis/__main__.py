"""graftlint CLI: ``python -m kubernetes_tpu.analysis`` (or ``make lint``).

Default mode runs the eight import-light static passes (guarded-by,
purity, registry, lock-order, tensor-contract, atomicity, coherence,
obligations) over the repository's ``kubernetes_tpu`` tree, subtracts
the reviewed baseline, and exits non-zero on any new finding OR any
stale baseline entry (the baseline only shrinks).

``--shapes`` mode (``make lint-shapes``) runs the JAX-backed
recompile-discipline pass instead — eval_shape over the pad-bucket
lattice plus real-encoder shape validation (analysis/shapes.py).  It is
a separate mode on purpose: the default lint must never initialize JAX.

``--interleave`` mode runs graftsched — the deterministic interleaving
explorer over the scenario library (analysis/interleave.py +
analysis/scenarios.py; ``make race`` is the deep pytest driver) — also
its own mode because the scheduler scenarios import JAX.

``--coherence`` mode (``make lint-coherence``) runs graftcoh's static
half alone — the resident-cache discipline matrix (analysis/
coherence.py).  It stays import-light and also rides the default mode;
the focused mode exists for triage symmetry with ``--shapes`` /
``--interleave``.  The runtime half is the GRAFTLINT_COHERENCE=1 epoch
auditor (analysis/epochs.py).

``--obligations`` mode (``make lint-obligations``) runs graftobl's
static half alone — the linear-obligation engine (analysis/
obligations.py): every popped pod / arbiter slot / APF seat / cache
assume / inflight counter / armed fault registry must be discharged
exactly once on every outgoing path.  Also import-light, also rides
the default mode.  The runtime half is the GRAFTLINT_OBLIGATIONS=1
exactly-once ledger (analysis/ledger.py).
"""

from __future__ import annotations

import argparse
import os
import sys

from . import (
    CHECK_IDS,
    STATIC_CHECK_IDS,
    apply_baseline,
    default_baseline_path,
    load_baseline,
    run_all,
    save_baseline,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="graftlint: project-native static analysis",
    )
    parser.add_argument(
        "root",
        nargs="?",
        default=None,
        help="repository root (default: the directory containing the "
        "kubernetes_tpu package)",
    )
    parser.add_argument(
        "--checks",
        default=",".join(STATIC_CHECK_IDS),
        help=f"comma-separated subset of {', '.join(STATIC_CHECK_IDS)} "
        "(ignored under --shapes)",
    )
    parser.add_argument(
        "--shapes",
        action="store_true",
        help="run the recompile-discipline pass (imports JAX; use "
        "JAX_PLATFORMS=cpu for a hardware-free run)",
    )
    parser.add_argument(
        "--coherence",
        action="store_true",
        help="run only the coherence (graftcoh) static pass — the "
        "resident-cache discipline matrix (import-light; it also rides "
        "the default mode)",
    )
    parser.add_argument(
        "--obligations",
        action="store_true",
        help="run only the obligations (graftobl) static pass — the "
        "linear-obligation engine over pods/slots/seats/assumes "
        "(import-light; it also rides the default mode)",
    )
    parser.add_argument(
        "--interleave",
        action="store_true",
        help="run the graftsched interleaving explorer over the scenario "
        "library (imports JAX for the scheduler scenarios; "
        "JAX_PLATFORMS=cpu works)",
    )
    parser.add_argument(
        "--scenario",
        default=None,
        help="with --interleave: run only this scenario (default: all)",
    )
    parser.add_argument(
        "--seeds",
        type=int,
        default=10,
        help="with --interleave: seeds per policy per scenario "
        "(schedules = 2 * seeds; default 10)",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: kubernetes_tpu/analysis/baseline.json)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline to exactly the current findings "
        "(requires review: every entry must be justified)",
    )
    args = parser.parse_args(argv)

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if args.interleave:
        return _run_interleave(args)
    if args.shapes:
        from . import shapes

        checks = ["recompile-discipline"]
        findings = shapes.check(root)
    elif args.coherence:
        checks = ["coherence"]
        findings = run_all(root, checks=checks)
    elif args.obligations:
        checks = ["obligations"]
        findings = run_all(root, checks=checks)
    else:
        checks = [c.strip() for c in args.checks.split(",") if c.strip()]
        unknown = [c for c in checks if c not in CHECK_IDS]
        if unknown:
            print(f"unknown checks: {', '.join(unknown)}", file=sys.stderr)
            return 2
        if "recompile-discipline" in checks:
            print(
                "recompile-discipline runs under --shapes (it imports JAX)",
                file=sys.stderr,
            )
            return 2
        findings = run_all(root, checks=checks)
    baseline_path = args.baseline or default_baseline_path()

    if args.write_baseline:
        save_baseline(baseline_path, findings)
        print(
            f"graftlint: wrote {len(findings)} baseline entries to "
            f"{baseline_path}"
        )
        return 0

    baseline = load_baseline(baseline_path)
    # baseline entries belong to the mode that produced them: the shape
    # mode must not mark the static passes' entries stale and vice versa
    relevant = [b for b in baseline if b.get("check") in checks]
    new, stale = apply_baseline(findings, relevant)

    for f in new:
        print(f.render())
    for entry in stale:
        print(
            f"stale baseline entry (finding no longer occurs — remove it): "
            f"{entry}",
        )
    n_grandfathered = len(findings) - len(new)
    summary = (
        f"graftlint: {len(new)} finding(s), {n_grandfathered} grandfathered, "
        f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'} "
        f"across {len(checks)} check(s)"
    )
    print(summary)
    return 1 if new or stale else 0


def _run_interleave(args) -> int:
    """graftsched CLI mode: sweep the scenario library, every schedule
    must pass its oracles; a failure prints the seed/policy so the
    schedule replays exactly (docs/static_analysis.md triage)."""
    import logging

    from . import interleave, scenarios

    # the fault-plan scenarios exercise containment paths that log
    # loudly BY DESIGN; the CLI reports pass/fail, not the noise
    logging.disable(logging.ERROR)

    names = (
        [args.scenario] if args.scenario else list(scenarios.SCENARIOS)
    )
    unknown = [n for n in names if n not in scenarios.SCENARIOS]
    if unknown:
        print(
            f"unknown scenario(s): {', '.join(unknown)}; "
            f"available: {', '.join(scenarios.SCENARIOS)}",
            file=sys.stderr,
        )
        return 2
    failures = 0
    for name in names:
        cls = scenarios.SCENARIOS[name]
        for policy in ("random", "pct"):
            for seed in range(args.seeds):
                try:
                    ex = scenarios.run_schedule(cls, seed, policy=policy)
                except Exception as e:  # noqa: BLE001 — report and continue
                    failures += 1
                    print(
                        f"FAIL {name} seed={seed} policy={policy}: "
                        f"{type(e).__name__}: {e}"
                    )
                    continue
        print(
            f"graftsched: {name}: {2 * args.seeds} schedules explored "
            f"({interleave.TOTALS['yield_points']} yield points total)"
        )
    print(
        f"graftsched: {interleave.TOTALS['schedules']} schedules, "
        f"{interleave.TOTALS['yield_points']} yield points, "
        f"{failures} failure(s) across {len(names)} scenario(s)"
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
