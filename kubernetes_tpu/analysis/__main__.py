"""graftlint CLI: ``python -m kubernetes_tpu.analysis`` (or ``make lint``).

Default mode runs the five import-light static passes over the
repository's ``kubernetes_tpu`` tree, subtracts the reviewed baseline,
and exits non-zero on any new finding OR any stale baseline entry (the
baseline only shrinks).

``--shapes`` mode (``make lint-shapes``) runs the JAX-backed
recompile-discipline pass instead — eval_shape over the pad-bucket
lattice plus real-encoder shape validation (analysis/shapes.py).  It is
a separate mode on purpose: the default lint must never initialize JAX.
"""

from __future__ import annotations

import argparse
import os
import sys

from . import (
    CHECK_IDS,
    STATIC_CHECK_IDS,
    apply_baseline,
    default_baseline_path,
    load_baseline,
    run_all,
    save_baseline,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="graftlint: project-native static analysis",
    )
    parser.add_argument(
        "root",
        nargs="?",
        default=None,
        help="repository root (default: the directory containing the "
        "kubernetes_tpu package)",
    )
    parser.add_argument(
        "--checks",
        default=",".join(STATIC_CHECK_IDS),
        help=f"comma-separated subset of {', '.join(STATIC_CHECK_IDS)} "
        "(ignored under --shapes)",
    )
    parser.add_argument(
        "--shapes",
        action="store_true",
        help="run the recompile-discipline pass (imports JAX; use "
        "JAX_PLATFORMS=cpu for a hardware-free run)",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: kubernetes_tpu/analysis/baseline.json)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline to exactly the current findings "
        "(requires review: every entry must be justified)",
    )
    args = parser.parse_args(argv)

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if args.shapes:
        from . import shapes

        checks = ["recompile-discipline"]
        findings = shapes.check(root)
    else:
        checks = [c.strip() for c in args.checks.split(",") if c.strip()]
        unknown = [c for c in checks if c not in CHECK_IDS]
        if unknown:
            print(f"unknown checks: {', '.join(unknown)}", file=sys.stderr)
            return 2
        if "recompile-discipline" in checks:
            print(
                "recompile-discipline runs under --shapes (it imports JAX)",
                file=sys.stderr,
            )
            return 2
        findings = run_all(root, checks=checks)
    baseline_path = args.baseline or default_baseline_path()

    if args.write_baseline:
        save_baseline(baseline_path, findings)
        print(
            f"graftlint: wrote {len(findings)} baseline entries to "
            f"{baseline_path}"
        )
        return 0

    baseline = load_baseline(baseline_path)
    # baseline entries belong to the mode that produced them: the shape
    # mode must not mark the static passes' entries stale and vice versa
    relevant = [b for b in baseline if b.get("check") in checks]
    new, stale = apply_baseline(findings, relevant)

    for f in new:
        print(f.render())
    for entry in stale:
        print(
            f"stale baseline entry (finding no longer occurs — remove it): "
            f"{entry}",
        )
    n_grandfathered = len(findings) - len(new)
    summary = (
        f"graftlint: {len(new)} finding(s), {n_grandfathered} grandfathered, "
        f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'} "
        f"across {len(checks)} check(s)"
    )
    print(summary)
    return 1 if new or stale else 0


if __name__ == "__main__":
    sys.exit(main())
