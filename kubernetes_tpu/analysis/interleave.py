"""graftsched — deterministic interleaving explorer for the control
plane's thread zoo.

The runtime lock-order tracker (analysis/runtime.py) RECORDS what the
OS scheduler happened to do; this module DECIDES what the scheduler
does.  While an :class:`Explorer` is installed, every
``threading.Lock`` / ``RLock`` / ``Condition`` created in the window is
a virtual primitive and every ``threading.Thread`` started in the
window is a managed thread: all managed threads serialize through a
single control token, handing it back at *yield points* — lock
acquire/release, condition wait/notify, ``faults.fire`` sites,
``time.sleep`` — where a seeded policy picks who runs next.  One seed =
one schedule = one byte-identical trace, so any failing interleaving
replays exactly (the chaos suite's property, but over SCHEDULES instead
of fault plans: chaos is probabilistic, graftsched is systematic).

Policies (both seeded):

random
    uniform random walk over the eligible threads at every step — the
    baseline explorer; good at shallow races.
pct
    PCT-style priority scheduling (Burckhardt et al.): each thread gets
    a random priority at spawn, the highest-priority eligible thread
    runs, and at ``depth`` pre-drawn step indices the running thread's
    priority drops to the floor — far better than random for races
    that need several ORDERED context switches.

Timeouts are virtual: ``time.monotonic``/``time.time`` serve a logical
clock, ``time.sleep`` advances it, and a TIMED condition wait is always
eligible to fire as a timeout (the policy choosing it advances the
clock past the deadline) — so every bounded-wait path in the tree is
explorable without wall-clock cost, and an UNTIMED wait with nobody
left to notify it is a detected deadlock, not a hang.

Blocking semantics are faithful where it matters: ``notify(n)`` is
consumed FIFO, and a waiter that already timed out (but has not yet
resumed) still eats the notification — CPython's lost-wakeup window —
so predicate-loop discipline is actually exercised.

Ground rules for scenarios (analysis/scenarios.py has the library):

  * build shared objects (stores, queues, caches) INSIDE the installed
    window so their locks are virtual, from the controller thread,
    BEFORE spawning workers;
  * after workers start, the controller only schedules — shared state
    is touched from managed threads (oracles run via ``run_inline``);
  * pass ``explorer.clock`` as the ``clock=`` argument to components
    that default it at import time (SchedulingQueue, Scheduler) — the
    ``time.monotonic`` patch cannot reach an already-bound default;
  * real blocking calls (``queue.SimpleQueue.get``, socket reads)
    inside the window wedge the schedule and are reported as such.

Nothing here imports JAX; scenarios that drive the scheduler do.
"""

from __future__ import annotations

import contextlib
import threading
import time as _time_mod
from random import Random
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..testing import faults as _faults

# -- module-wide exploration counters (mirrored into the scheduler
# Registry as scheduler_interleave_* via mirror_metrics) ---------------------

TOTALS = {"schedules": 0, "yield_points": 0}


def mirror_metrics(registry, atomicity_findings: int = 0) -> None:
    """Export the exploration counters (and, when the caller just ran
    the static pass, its finding count) through a scheduler metrics
    Registry — perf/collectors.py SCALAR_METRICS keeps the surface
    reconciled by the graftlint registry pass."""
    registry.interleave_schedules_total.set(float(TOTALS["schedules"]))
    registry.interleave_yield_points.set(float(TOTALS["yield_points"]))
    registry.atomicity_findings.set(float(atomicity_findings))


class DeadlockError(AssertionError):
    """No eligible thread, but foreground work remains."""


class ScheduleBudgetExceeded(AssertionError):
    """The schedule ran past its step budget without quiescing."""


_DONE = "done"
_LIVE = "live"

# cv-waiter entry states
_WAITING = "waiting"
_NOTIFIED = "notified"
_TIMEDOUT = "timedout"


class _Gate:
    """A real event built from pre-patch primitives (threading.Event
    would hand back a virtual-backed one while the patch is live, and
    the deadline math below must use the pre-patch wall clock)."""

    def __init__(self, real_lock_ctor, real_cond_ctor, real_clock):
        self._cond = real_cond_ctor(real_lock_ctor())
        self._clock = real_clock
        self._flag = False

    def set(self) -> None:
        with self._cond:
            self._flag = True
            self._cond.notify_all()

    def clear(self) -> None:
        with self._cond:
            self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        with self._cond:
            if timeout is None:
                while not self._flag:
                    self._cond.wait()
                return True
            deadline = self._clock() + timeout
            while not self._flag:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True


class _Rec:
    """One managed thread's scheduler-side record."""

    __slots__ = (
        "name", "index", "ident", "gate", "state", "parked", "blocked_on",
        "background", "priority", "exc", "where",
    )

    def __init__(self, name: str, index: int, gate: _Gate, background: bool):
        self.name = name
        self.index = index
        self.ident: Optional[int] = None
        self.gate = gate
        self.state = _LIVE
        self.parked = False
        # None | ("lock", VirtualLock) | ("cv", _CvEntry) | ("join", _Rec)
        self.blocked_on: Optional[Tuple[str, Any]] = None
        self.background = background
        self.priority = 0.0
        self.exc: Optional[BaseException] = None
        self.where = "spawn"

    def __repr__(self):
        return f"<_Rec {self.name} {self.state} at {self.where}>"


class _CvEntry:
    __slots__ = ("rec", "state", "timed", "timeout")

    def __init__(self, rec: _Rec, timed: bool, timeout: float):
        self.rec = rec
        self.state = _WAITING
        self.timed = timed
        self.timeout = timeout


class VirtualLock:
    """Lock/RLock stand-in.  Managed threads use the cooperative
    protocol (ownership is scheduler bookkeeping — serialization makes
    a real mutex redundant); unmanaged threads (controller setup and
    teardown, or any thread after detach) fall through to a real
    lock."""

    def __init__(self, explorer: "Explorer", reentrant: bool, name: str):
        self._ex = explorer
        self._reentrant = reentrant
        self.name = name
        self.owner: Optional[_Rec] = None
        self.count = 0
        self._real = (
            explorer._real_rlock() if reentrant else explorer._real_lock()
        )

    def acquire(self, blocking: bool = True, timeout: float = -1):
        rec = self._ex._current_rec()
        if rec is None:
            if self.owner is not None:
                raise RuntimeError(
                    f"unmanaged acquire of {self.name} while virtually "
                    f"owned by {self.owner.name} — touch shared state "
                    "only from managed threads while exploring"
                )
            return self._real.acquire(blocking, timeout)
        if self.owner is rec:
            if not self._reentrant:
                raise RuntimeError(
                    f"non-reentrant {self.name} re-acquired by {rec.name}"
                )
            self.count += 1
            return True
        if not blocking:
            self._ex._yield(rec, f"tryacquire:{self.name}")
            if self.owner is None:
                self.owner, self.count = rec, 1
                return True
            return False
        self._ex._block_on_lock(rec, self)
        return True

    def release(self):
        rec = self._ex._current_rec()
        if rec is None:
            return self._real.release()
        if self.owner is not rec:
            raise RuntimeError(
                f"release of {self.name} by non-owner {rec.name}"
            )
        self.count -= 1
        if self.count == 0:
            self.owner = None
            self._ex._yield(rec, f"release:{self.name}")

    def locked(self):
        if self._ex._current_rec() is None:
            return (
                self._real.locked() if hasattr(self._real, "locked")
                else self.owner is not None
            )
        return self.owner is not None

    def _at_fork_reinit(self):
        # os.register_at_fork hooks captured by imports inside the
        # window (concurrent.futures.thread) land here
        return self._real._at_fork_reinit()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition-over-lock hooks (our VirtualCondition and any stdlib
    # machinery built on a patched Lock use these)
    def _is_owned(self):
        rec = self._ex._current_rec()
        if rec is None:
            if hasattr(self._real, "_is_owned"):
                return self._real._is_owned()
            if self._real.acquire(False):
                self._real.release()
                return False
            return True
        return self.owner is rec

    def _release_save(self):
        rec = self._ex._current_rec()
        if rec is None:
            if hasattr(self._real, "_release_save"):
                return self._real._release_save()
            self._real.release()
            return 1
        count, self.count, self.owner = self.count, 0, None
        return count

    def _acquire_restore(self, state):
        rec = self._ex._current_rec()
        if rec is None:
            if hasattr(self._real, "_acquire_restore"):
                return self._real._acquire_restore(state)
            return self._real.acquire()
        self._ex._block_on_lock(rec, self)
        self.count = state

    def __repr__(self):
        who = self.owner.name if self.owner else None
        return f"<VirtualLock {self.name} owner={who} n={self.count}>"


class VirtualCondition:
    """Condition stand-in over a VirtualLock, with faithful FIFO notify
    consumption (a timed-out-but-not-yet-resumed waiter still eats a
    notify — the CPython lost-wakeup window predicate loops exist
    for)."""

    def __init__(self, explorer: "Explorer", lock=None, name: str = "cv"):
        self._ex = explorer
        self.name = name
        if lock is None:
            lock = VirtualLock(explorer, reentrant=True, name=f"{name}.lock")
        self._vlock = lock
        self._waiters: List[_CvEntry] = []
        inner = lock._real if isinstance(lock, VirtualLock) else lock
        self._real = explorer._real_condition(inner)

    # lock surface forwards
    def acquire(self, *a, **k):
        return self._vlock.acquire(*a, **k)

    def release(self):
        return self._vlock.release()

    def __enter__(self):
        self._vlock.acquire()
        return self

    def __exit__(self, *exc):
        self._vlock.release()
        return False

    def _is_owned(self):
        return self._vlock._is_owned()

    def wait(self, timeout: Optional[float] = None) -> bool:
        rec = self._ex._current_rec()
        if rec is None:
            # detached/unmanaged: bounded real wait so leftover service
            # loops cycle quickly toward their exit checks
            t = 0.02 if timeout is None else min(timeout, 0.02)
            return self._real.wait(t)
        if self._vlock.owner is not rec:
            raise RuntimeError(f"wait on {self.name} without its lock")
        entry = _CvEntry(
            rec, timed=timeout is not None, timeout=timeout or 0.0
        )
        self._waiters.append(entry)
        saved = self._vlock._release_save()
        self._ex._block_on_cv(rec, entry, self)
        try:
            self._waiters.remove(entry)
        except ValueError:
            pass
        self._vlock._acquire_restore(saved)
        return entry.state == _NOTIFIED

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # self-contained: the stdlib helper computes deadlines with the
        # REAL clock, which spins against the virtual one
        end = None if timeout is None else self._ex.clock() + timeout
        result = predicate()
        while not result:
            remaining = None
            if end is not None:
                remaining = end - self._ex.clock()
                if remaining <= 0:
                    break
            self.wait(remaining)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        rec = self._ex._current_rec()
        if rec is None:
            return self._real.notify(n)
        self._ex._yield(rec, f"notify:{self.name}")
        consumed = 0
        for entry in self._waiters:
            if consumed >= n:
                break
            if entry.state == _WAITING:
                entry.state = _NOTIFIED
                consumed += 1
            elif entry.state == _TIMEDOUT:
                # the CPython window: a notify landing on a waiter that
                # timed out internally but has not yet resumed is WASTED
                consumed += 1

    def notify_all(self) -> None:
        rec = self._ex._current_rec()
        if rec is None:
            return self._real.notify_all()
        self._ex._yield(rec, f"notifyall:{self.name}")
        for entry in self._waiters:
            if entry.state == _WAITING:
                entry.state = _NOTIFIED

    notifyAll = notify_all

    def __repr__(self):
        return f"<VirtualCondition {self.name} waiters={len(self._waiters)}>"


class Explorer:
    """One schedule's cooperative scheduler.  Use via :meth:`installed`
    (patches threading/time/faults for the dynamic extent), spawn
    foreground work with :meth:`spawn`, then :meth:`drive` to run the
    schedule to quiescence and :meth:`run_inline` for oracles."""

    def __init__(
        self,
        seed: int = 0,
        policy: str = "random",
        pct_depth: int = 3,
        max_steps: int = 50_000,
    ):
        if policy not in ("random", "pct"):
            raise ValueError(f"unknown policy {policy!r}")
        self.seed = seed
        self.policy = policy
        self.max_steps = max_steps
        self.rng = Random(seed * 1_000_003 + (0 if policy == "random" else 1))
        self.steps = 0
        self.trace: List[Tuple[int, str, str]] = []
        self._clock = 1000.0
        self._recs: List[_Rec] = []
        self._by_ident: Dict[int, _Rec] = {}
        self.active = False
        self._installed = False
        self._real_lock = threading.Lock
        self._real_rlock = threading.RLock
        self._real_condition = threading.Condition
        self._real_thread = threading.Thread
        self._real_monotonic = _time_mod.monotonic
        self._mu = None
        self._ctl = None          # _Gate: threads -> controller
        self._saved: Dict[str, Any] = {}
        self._spawn_i = 0
        self._prio_floor = -1.0
        # PCT change points are drawn over a horizon matched to real
        # schedule lengths (a few hundred steps), not max_steps — points
        # past the schedule's natural end would never fire
        self._pct_changes = set()
        if policy == "pct":
            horizon = min(2048, max_steps)
            self._pct_changes = {
                self.rng.randrange(1, horizon) for _ in range(pct_depth)
            }

    # -- virtual clock -----------------------------------------------------

    def clock(self) -> float:
        return self._clock

    def _advance(self, dt: float) -> None:
        self._clock += dt

    # -- install/uninstall -------------------------------------------------

    @contextlib.contextmanager
    def installed(self):
        """Patch threading.Lock/RLock/Condition/Thread, time.monotonic/
        time/sleep and faults.fire for the dynamic extent; restore on
        exit and detach any still-live managed threads (service loops
        then run against real primitives and exit via their own
        stop-flag/weakref checks)."""
        if self._installed:
            raise RuntimeError("explorer already installed")
        # capture the CURRENT ctors (possibly the lock-order tracker's
        # wrappers — real behavior either way) before replacing them
        self._real_lock = threading.Lock
        self._real_rlock = threading.RLock
        self._real_condition = threading.Condition
        self._real_thread = threading.Thread
        self._real_monotonic = _time_mod.monotonic
        self._mu = self._real_lock()
        self._ctl = _Gate(
            self._real_lock, self._real_condition, self._real_monotonic
        )
        self._saved = dict(
            Lock=threading.Lock,
            RLock=threading.RLock,
            Condition=threading.Condition,
            Thread=threading.Thread,
            monotonic=_time_mod.monotonic,
            time=_time_mod.time,
            sleep=_time_mod.sleep,
            fire=_faults.fire,
        )
        ex = self

        def make_lock():
            return VirtualLock(ex, reentrant=False, name=f"L{ex._name_seq()}")

        def make_rlock():
            return VirtualLock(ex, reentrant=True, name=f"R{ex._name_seq()}")

        def make_condition(lock=None):
            return VirtualCondition(ex, lock, name=f"C{ex._name_seq()}")

        real_thread = self._real_thread

        class ManagedThread(real_thread):
            """Threads STARTED while the explorer is active register as
            managed background threads and serialize through it."""

            def start(self):
                if not ex.active:
                    return super().start()
                rec = ex._register(
                    self.name or f"thread-{ex._spawn_i}", background=True
                )
                self._graftsched_rec = rec
                run = self.run

                def bootstrap():
                    ex._bootstrap(rec, run)

                runner = real_thread(
                    target=bootstrap, name=self.name, daemon=True
                )
                self._graftsched_runner = runner
                runner.start()

            def is_alive(self):
                runner = getattr(self, "_graftsched_runner", None)
                if runner is not None:
                    return runner.is_alive()
                return super().is_alive()

            def join(self, timeout=None):
                runner = getattr(self, "_graftsched_runner", None)
                rec = getattr(self, "_graftsched_rec", None)
                me = ex._current_rec()
                if rec is not None and me is not None and ex.active:
                    ex._block_on_join(me, rec)
                    return
                if runner is not None:
                    return runner.join(timeout)
                return super().join(timeout)

        def v_monotonic():
            return ex._clock

        def v_time():
            return 1_700_000_000.0 + ex._clock

        def v_sleep(seconds):
            rec = ex._current_rec()
            if rec is None:
                return  # controller/unmanaged: virtual time is free
            ex._advance(max(float(seconds), 0.0))
            ex._yield(rec, f"sleep:{seconds}")

        saved_fire = self._saved["fire"]

        def v_fire(point, **ctx):
            rec = ex._current_rec()
            if rec is not None:
                ex._yield(rec, f"fault:{point}")
            return saved_fire(point, **ctx)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        threading.Condition = make_condition
        threading.Thread = ManagedThread
        _time_mod.monotonic = v_monotonic
        _time_mod.time = v_time
        _time_mod.sleep = v_sleep
        _faults.fire = v_fire
        armed_here = False
        if _faults._registry is None:
            # gated fire sites check the registry before calling; arm an
            # empty plan so every site becomes a yield point
            _faults.arm(_faults.FaultRegistry(self.seed))
            armed_here = True
        self.active = True
        self._installed = True
        try:
            yield self
        finally:
            self.active = False
            self._detach_all()
            threading.Lock = self._saved["Lock"]
            threading.RLock = self._saved["RLock"]
            threading.Condition = self._saved["Condition"]
            threading.Thread = self._saved["Thread"]
            _time_mod.monotonic = self._saved["monotonic"]
            _time_mod.time = self._saved["time"]
            _time_mod.sleep = self._saved["sleep"]
            _faults.fire = self._saved["fire"]
            if armed_here:
                _faults.disarm()
            self._installed = False
            TOTALS["schedules"] += 1

    def _detach_all(self) -> None:
        """Open every parked thread's gate; with ``active`` False their
        next yield/wait is a no-op/real-wait and service loops run
        free."""
        with self._mu:
            recs = list(self._recs)
        for rec in recs:
            if rec.state != _DONE:
                rec.gate.set()

    def _name_seq(self) -> int:
        self._spawn_i += 1
        return self._spawn_i

    # -- registration / bootstrap ------------------------------------------

    def _register(self, name: str, background: bool) -> _Rec:
        with self._mu:
            rec = _Rec(
                name,
                len(self._recs),
                _Gate(
                    self._real_lock, self._real_condition,
                    self._real_monotonic,
                ),
                background,
            )
            rec.priority = self.rng.random()
            self._recs.append(rec)
        return rec

    def spawn(
        self, fn: Callable, *args, name: Optional[str] = None
    ) -> _Rec:
        """Start a FOREGROUND managed thread running fn(*args) —
        :meth:`drive` runs until every foreground thread completes."""
        rec = self._register(name or fn.__name__, background=False)

        def bootstrap():
            self._bootstrap(rec, lambda: fn(*args))

        t = self._real_thread(target=bootstrap, name=rec.name, daemon=True)
        t.start()
        return rec

    def _bootstrap(self, rec: _Rec, target: Callable) -> None:
        rec.ident = threading.get_ident()
        with self._mu:
            self._by_ident[rec.ident] = rec
        self._yield(rec, "start")  # park until first scheduled
        try:
            target()
        except BaseException as e:  # noqa: BLE001 — recorded, not printed
            rec.exc = e
        finally:
            with self._mu:
                rec.state = _DONE
                rec.parked = True
                rec.blocked_on = None
                self._by_ident.pop(rec.ident, None)
            self._ctl.set()

    def _current_rec(self) -> Optional[_Rec]:
        if not self.active:
            return None
        return self._by_ident.get(threading.get_ident())

    # -- thread-side yield/block -------------------------------------------

    def _yield(self, rec: _Rec, label: str) -> None:
        """Pause at a yield point until the policy schedules this thread
        again.  After detach this is a no-op."""
        if not self.active:
            return
        rec.where = label
        with self._mu:
            rec.parked = True
        self._ctl.set()
        # the CONTROLLER flips rec.parked back to False before opening
        # the gate, so "every live thread parked" can never be observed
        # stale while this thread is already running again
        while not rec.gate.wait(timeout=60.0):
            if not self.active:
                break
            raise RuntimeError(
                f"controller stalled; {rec.name} abandoned at {label}"
            )
        rec.gate.clear()

    def _block_on_lock(self, rec: _Rec, lock: VirtualLock) -> None:
        rec.blocked_on = ("lock", lock)
        while True:
            self._yield(rec, f"acquire:{lock.name}")
            if not self.active:
                rec.blocked_on = None
                return  # detached: ownership bookkeeping is moot now
            if lock.owner is None:
                lock.owner, lock.count = rec, 1
                rec.blocked_on = None
                return

    def _block_on_cv(
        self, rec: _Rec, entry: _CvEntry, cv: VirtualCondition
    ) -> None:
        rec.blocked_on = ("cv", entry)
        while True:
            self._yield(rec, f"wait:{cv.name}")
            if not self.active:
                entry.state = _TIMEDOUT
                rec.blocked_on = None
                return
            if entry.state != _WAITING:
                rec.blocked_on = None
                return

    def _block_on_join(self, rec: _Rec, target: _Rec) -> None:
        rec.blocked_on = ("join", target)
        while True:
            self._yield(rec, f"join:{target.name}")
            if not self.active or target.state == _DONE:
                rec.blocked_on = None
                return

    # -- controller --------------------------------------------------------

    def _live(self) -> List[_Rec]:
        with self._mu:
            return [r for r in self._recs if r.state != _DONE]

    def _wait_all_parked(self) -> None:
        """Block until every live managed thread is parked at a yield
        point (only then is scheduler state consistent and only then is
        it safe for the controller to read scenario state)."""
        deadline = self._real_monotonic() + 60.0
        while True:
            with self._mu:
                pending = [
                    r for r in self._recs
                    if r.state != _DONE and not r.parked
                ]
            if not pending:
                return
            if self._real_monotonic() > deadline:
                names = ", ".join(f"{r.name}@{r.where}" for r in pending)
                raise RuntimeError(
                    f"managed thread(s) wedged (real blocking call inside "
                    f"the exploration window?): {names}"
                )
            self._ctl.wait(timeout=0.5)
            self._ctl.clear()

    def _eligible(self) -> List[Tuple[_Rec, str]]:
        """(rec, action) pairs the policy may pick: 'run' resumes the
        thread; 'timeout' fires a timed cv wait."""
        out: List[Tuple[_Rec, str]] = []
        for rec in self._recs:
            if rec.state == _DONE or rec.ident is None:
                continue
            b = rec.blocked_on
            if b is None:
                out.append((rec, "run"))
            elif b[0] == "lock":
                if b[1].owner is None:
                    out.append((rec, "run"))
            elif b[0] == "cv":
                entry: _CvEntry = b[1]
                if entry.state != _WAITING:
                    out.append((rec, "run"))
                elif entry.timed:
                    out.append((rec, "timeout"))
            elif b[0] == "join":
                if b[1].state == _DONE:
                    out.append((rec, "run"))
        return out

    def _demote(self, rec: _Rec) -> None:
        self._prio_floor -= 1.0
        rec.priority = self._prio_floor

    def _pick(self, eligible: List[Tuple[_Rec, str]]) -> Tuple[_Rec, str]:
        if self.policy == "pct":
            best = max(eligible, key=lambda e: (e[0].priority, -e[0].index))
            if self.steps in self._pct_changes:
                self._demote(best[0])
            elif best[1] == "timeout":
                # firing a timed wait means its full timeout elapsed on
                # the virtual clock — every runnable thread would have
                # run in that window, so the waiter drops below them
                # (this also breaks idle-spin starvation under PCT)
                self._demote(best[0])
            return best
        return eligible[self.rng.randrange(len(eligible))]

    def _step(self) -> bool:
        """Schedule one thread for one hop.  False when no live managed
        thread can make progress (all done, or only untimed-parked
        background threads remain)."""
        self._wait_all_parked()
        if not self._live():
            return False
        eligible = self._eligible()
        if not eligible:
            live = self._live()
            fg = [r for r in live if not r.background]
            where = ", ".join(f"{r.name}@{r.where}" for r in live)
            if fg:
                raise DeadlockError(
                    f"deadlock: no eligible thread among [{where}] "
                    f"(seed={self.seed}, policy={self.policy}, "
                    f"step={self.steps}); trace tail: {self.trace[-8:]}"
                )
            return False
        rec, action = self._pick(eligible)
        self.steps += 1
        TOTALS["yield_points"] += 1
        self._advance(0.0005)
        if action == "timeout":
            entry: _CvEntry = rec.blocked_on[1]
            entry.state = _TIMEDOUT
            self._advance(max(entry.timeout, 0.0))
        self.trace.append((self.steps, rec.name, rec.where))
        self._ctl.clear()
        with self._mu:
            rec.parked = False
        rec.gate.set()
        self._wait_all_parked()
        return True

    def drive(
        self,
        quiesce: Optional[Callable[[], bool]] = None,
        max_extra_steps: int = 5_000,
    ) -> None:
        """Run the schedule: step until every foreground thread is done,
        then (with ``quiesce``) keep scheduling background threads until
        the predicate holds.  Raises DeadlockError /
        ScheduleBudgetExceeded on failure; re-raises the first
        foreground thread's exception if one died."""
        while True:
            if self.steps > self.max_steps:
                dead = [
                    f"{r.name}: {r.exc!r}"
                    for r in self._recs if r.exc is not None
                ]
                raise ScheduleBudgetExceeded(
                    f"schedule exceeded {self.max_steps} steps "
                    f"(seed={self.seed}); dead threads: {dead or 'none'}; "
                    f"trace tail: {self.trace[-8:]}"
                )
            with self._mu:
                fg_live = any(
                    not r.background and r.state != _DONE for r in self._recs
                )
            if not fg_live:
                break
            if not self._step():
                break
        for name, exc in self.foreground_errors():
            raise exc
        if quiesce is not None:
            extra = 0
            while not quiesce():
                extra += 1
                if extra > max_extra_steps:
                    raise ScheduleBudgetExceeded(
                        f"quiesce predicate never held after {extra} extra "
                        f"steps (seed={self.seed})"
                    )
                if not self._step():
                    if not quiesce():
                        raise DeadlockError(
                            "background threads idle but quiesce predicate "
                            f"false (seed={self.seed})"
                        )
                    break

    def run_inline(self, fn: Callable, name: str = "oracle") -> None:
        """Run fn to completion as a managed foreground thread (oracles
        that touch shared state must participate in the schedule).
        Re-raises whatever fn raised."""
        rec = self.spawn(fn, name=name)
        budget = self.steps + 20_000
        while rec.state != _DONE:
            if self.steps > budget:
                raise ScheduleBudgetExceeded(
                    f"'{name}' never completed (seed={self.seed})"
                )
            if not self._step():
                break
        if rec.exc is not None:
            raise rec.exc

    def foreground_errors(self) -> List[Tuple[str, BaseException]]:
        with self._mu:
            return [
                (r.name, r.exc)
                for r in self._recs
                if not r.background and r.exc is not None
            ]

    def thread_names(self) -> List[str]:
        with self._mu:
            return [r.name for r in self._recs]
