"""Runtime retrace tracker — the dynamic half of recompile-discipline.

The static pass (analysis/shapes.py) proves the bucket lattice is
closed under ``jax.eval_shape``; this tracker observes the XLA traces
that ACTUALLY happen while code runs and answers two questions the
static pass cannot:

  * did any executable key get traced TWICE (a genuine retrace — cache
    eviction, a config flip, or a non-hashable static leaking into the
    jit key)?  Always a failure.
  * did any trace happen during the STEADY window (after the harness
    called :func:`mark_steady`)?  A steady-state trace means a kernel
    argument escaped the pad-bucket lattice and ate a 10-40 s XLA
    compile on the hot path — the exact failure mode the pad buckets
    (utils.vocab.pad_dim) exist to prevent.  bench.py gates on this
    under ``BENCH_STRICT=1``.

The solver jit wrappers (ops/assign.py ``greedy_assign_jit`` /
``wavefront_assign_jit``, ops/auction.py ``auction_assign_jit``) call
:func:`note` after every dispatch.  Disarmed cost is one module-global
None check; armed cost is one ``_cache_size()`` C-call plus — only on a
cache-size increase — one signature hash.

Usage (scoped, mirroring analysis/runtime.py's lock tracker)::

    from kubernetes_tpu.analysis import retrace

    with retrace.tracked() as tracker:
        ...                       # warmup: traces are expected
        retrace.mark_steady()
        ...                       # steady: any trace is a finding
    tracker.assert_no_steady_recompiles()

Under pytest, set ``GRAFTLINT_SHAPES=1`` to arm the tracker for the
whole session (tests/conftest.py wires the fixture); the session fails
if any executable key was traced twice.

This module is import-light (no JAX import at module scope): the
trackers only touch JAX objects handed to them by already-jitted code.
"""

from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Callable, Dict, List, Optional, Tuple


class RetraceViolation(AssertionError):
    """An executable key was traced when the discipline forbids it."""


class RetraceTracker:
    def __init__(self):
        self._mu = threading.Lock()
        # id(jitfn) -> (weakref-or-None, token, last cache size).  The
        # weakref detects id reuse after GC: duplicate-trace keys are
        # scoped per EXECUTABLE CACHE (two scheduler instances tracing
        # the same signature is normal; one cache tracing it twice is
        # eviction or an unstable static), so a recycled id must get a
        # fresh token, not inherit a dead cache's history.
        self._fns: Dict[int, Tuple[Optional[weakref.ref], int, int]] = {}
        self._next_token = 0
        self._seen: Dict[Tuple[str, int, object], int] = {}  # -> trace count
        self._steady = False
        self.traces: List[Tuple[str, bool]] = []   # (label, was_steady)
        self.steady_events: List[str] = []
        self.duplicates: List[str] = []

    def _entry(self, jitfn) -> Tuple[int, int]:
        """(token, last size) for this jit object, id-reuse safe."""
        ent = self._fns.get(id(jitfn))
        if ent is not None and (ent[0] is None or ent[0]() is jitfn):
            return ent[1], ent[2]
        try:
            ref: Optional[weakref.ref] = weakref.ref(jitfn)
        except TypeError:
            ref = None
        token = self._next_token
        self._next_token += 1
        self._fns[id(jitfn)] = (ref, token, 0)
        return token, 0

    # -- recording ---------------------------------------------------------

    def note(self, label: str, jitfn, key_fn: Callable[[], object]) -> None:
        """Record a trace if `jitfn`'s executable cache grew since the
        last note.  key_fn is only evaluated on a cache-size increase."""
        size_of = getattr(jitfn, "_cache_size", None)
        if size_of is None:
            return
        try:
            size = size_of()
        except Exception:  # noqa: BLE001 — observability must not fault
            return
        with self._mu:
            token, prev = self._entry(jitfn)
            ref = self._fns[id(jitfn)][0]
            self._fns[id(jitfn)] = (ref, token, size)
            if size <= prev:
                return
            steady = self._steady
        key = (label, token, key_fn())
        with self._mu:
            n = self._seen.get(key, 0)
            self._seen[key] = n + 1
            self.traces.append((label, steady))
            if n > 0:
                self.duplicates.append(
                    f"executable key for '{label}' traced {n + 1} times "
                    f"(signature {key[2]!r}) — the compile cache is not "
                    "holding this key"
                )
            if steady:
                self.steady_events.append(
                    f"steady-state retrace of '{label}' "
                    f"(signature {key[2]!r}) — a kernel argument escaped "
                    "the pad-bucket lattice"
                )

    # -- steady window -----------------------------------------------------

    def mark_steady(self) -> None:
        """Warmup is over: every later trace is a steady-state recompile."""
        with self._mu:
            self._steady = True

    def clear_steady(self) -> None:
        with self._mu:
            self._steady = False

    # -- results -----------------------------------------------------------

    @property
    def total(self) -> int:
        with self._mu:
            return len(self.traces)

    @property
    def steady_total(self) -> int:
        with self._mu:
            return len(self.steady_events)

    def assert_no_steady_recompiles(self) -> None:
        if self.steady_events:
            raise RetraceViolation("\n".join(self.steady_events[:20]))

    def assert_no_duplicate_traces(self) -> None:
        if self.duplicates:
            raise RetraceViolation("\n".join(self.duplicates[:20]))


_active: Optional[RetraceTracker] = None


@contextlib.contextmanager
def tracked(tracker: Optional[RetraceTracker] = None):
    """Arm retrace tracking for the dynamic extent of the context.
    Nested arming shares the outer tracker (session fixture + per-test
    use must not shadow each other)."""
    global _active
    if _active is not None:
        yield _active
        return
    tracker = tracker or RetraceTracker()
    _active = tracker
    try:
        yield tracker
    finally:
        _active = None


def active() -> Optional[RetraceTracker]:
    return _active


def note(label: str, jitfn, key_fn: Callable[[], object]) -> None:
    """Module-level hook the jit wrappers call: no-op unless a tracker
    is armed (one global None check disarmed)."""
    t = _active
    if t is not None:
        t.note(label, jitfn, key_fn)


def mark_steady() -> None:
    t = _active
    if t is not None:
        t.mark_steady()


def clear_steady() -> None:
    t = _active
    if t is not None:
        t.clear_steady()


def steady_total() -> int:
    t = _active
    return t.steady_total if t is not None else 0


def total() -> int:
    t = _active
    return t.total if t is not None else 0


def signature(tree, statics: tuple = ()) -> tuple:
    """Hashable abstract signature of a pytree of arrays + the static
    args: exactly the pieces that key an XLA executable."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    return (
        tuple(
            (tuple(getattr(l, "shape", ())), str(getattr(l, "dtype", type(l))))
            for l in leaves
        ),
        statics,
    )
