"""guarded-by: lock-discipline enforcement for annotated shared fields.

A class declares its lock-guarded state either way:

    class Store:
        GUARDED_FIELDS = {"_objects": "_lock", "_rv": "_lock"}
        LOCKED_METHODS = frozenset({"_dispatch"})  # caller holds the lock

or inline in ``__init__``::

        self._assumed = {}   # guarded_by: _lock

``GUARDED_FIELDS`` may also be a plain set/tuple of names (the lock
defaults to ``_lock``).  Every ``self.<field>`` read or write must then
sit lexically inside ``with self.<lock>:`` — closures defined inside
the block inherit it (the queue's pop helpers) — or live in an exempt
method:

  * ``__init__`` / ``__del__`` (the object is not shared yet / anymore);
  * names matching ``_locked_*`` or ``*_locked`` (the project's
    caller-holds-the-lock convention);
  * names listed in ``LOCKED_METHODS`` (reviewed: caller holds the lock,
    or the method runs in a single-threaded phase such as construction
    or registration-before-arming).

``# graftlint: disable=guarded-by`` on the access line suppresses one
finding (say why — usually a double-checked-locking fast path).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Set

from . import Finding, SourceFile, str_constants

CHECK = "guarded-by"

_INLINE_RE = re.compile(
    r"self\.(\w+)\s*(?::[^=]+)?=.*#\s*guarded_by:\s*(\w+)"
)

_EXEMPT_NAMES = {"__init__", "__del__", "__post_init__"}


def _class_decls(
    src: SourceFile, cls: ast.ClassDef
) -> tuple[Dict[str, str], Set[str]]:
    """(field -> lock, exempt method names) for one class."""
    guarded: Dict[str, str] = {}
    locked_methods: Set[str] = set()
    for stmt in cls.body:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        tgt = stmt.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        if tgt.id == "GUARDED_FIELDS":
            if isinstance(stmt.value, ast.Dict):
                for k, v in zip(stmt.value.keys, stmt.value.values):
                    if (
                        isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                    ):
                        guarded[k.value] = v.value
            else:
                for name in str_constants(stmt.value):
                    guarded[name] = "_lock"
        elif tgt.id == "LOCKED_METHODS":
            locked_methods.update(str_constants(stmt.value))
    # inline `# guarded_by: <lock>` comments anywhere in the class span
    end = getattr(cls, "end_lineno", None) or cls.lineno
    for lineno in range(cls.lineno, end + 1):
        if lineno - 1 < len(src.lines):
            m = _INLINE_RE.search(src.lines[lineno - 1])
            if m:
                guarded.setdefault(m.group(1), m.group(2))
    return guarded, locked_methods


def _method_exempt(name: str, locked_methods: Set[str]) -> bool:
    return (
        name in _EXEMPT_NAMES
        or name.startswith("_locked_")
        or name.endswith("_locked")
        or name in locked_methods
    )


def _with_locks(node: ast.With) -> Set[str]:
    """Lock attr names acquired by `with self.<attr>[, ...]:`."""
    out: Set[str] = set()
    for item in node.items:
        ctx = item.context_expr
        if (
            isinstance(ctx, ast.Attribute)
            and isinstance(ctx.value, ast.Name)
            and ctx.value.id == "self"
        ):
            out.add(ctx.attr)
    return out


def _check_method(
    src: SourceFile,
    cls_name: str,
    fn: ast.FunctionDef,
    guarded: Dict[str, str],
    findings: List[Finding],
) -> None:
    symbol = f"{cls_name}.{fn.name}"

    def visit(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, ast.With):
            held = held | _with_locks(node)
            for item in node.items:
                visit(item.context_expr, held)
            for stmt in node.body:
                visit(stmt, held)
            return
        if isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in guarded
            ):
                lock = guarded[node.attr]
                if lock not in held and not src.suppressed(node.lineno, CHECK):
                    findings.append(
                        Finding(
                            CHECK,
                            src.relpath,
                            node.lineno,
                            symbol,
                            f"field '{node.attr}' accessed outside "
                            f"'with self.{lock}'",
                        )
                    )
        # nested defs/lambdas inherit the lexical lock context: closures
        # defined under `with self._lock:` run with it held
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, frozenset())


def check(files: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            guarded, locked_methods = _class_decls(src, node)
            if not guarded:
                continue
            for stmt in node.body:
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and not _method_exempt(stmt.name, locked_methods):
                    _check_method(src, node.name, stmt, guarded, findings)
    return findings
