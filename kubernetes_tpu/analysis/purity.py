"""purity: hot-path functions must not host-sync, leak tracers, read
wall clocks, draw unseeded randomness, or take locks.

Roots are functions carrying the ``@hot_path`` decorator
(analysis/markers.py) — the solve kernels in ops/ and the dispatch path
in models/.  A call-graph walk over the ops/, models/ and parallel/
packages marks everything statically reachable from a root, then flags:

  * ``jax.device_get`` and ``.block_until_ready()`` / ``.item()`` calls
    (explicit host syncs);
  * ``np.asarray`` / ``np.array`` on the hot path (an implicit
    blocking device→host readback when handed a device array);
  * ``float(x)`` / ``int(x)`` where ``x`` contains a call or subscript —
    the tracer-leak shape (``float(scores[i])`` blocks; ``float(cfg_x)``
    on a plain name is config coercion and is allowed);
  * ``time.time()`` / ``time.monotonic()`` (wall clocks: hot-path code
    must be replayable and trace-stable);
  * module-level ``random.*`` draws (unseeded; seeded ``Random(seed)``
    instances and ``jax.random`` are fine);
  * lock acquisition: ``with <x>._lock/._mu/._cond`` or ``.acquire()``.

Call-edge resolution is deliberately conservative: same-module
functions, ``from x import y`` names, module-alias attributes,
``self.method`` within a class, and otherwise only attribute names that
are defined exactly once across the analyzed packages.  Unresolvable
calls are ignored (jit closures, stdlib).

``# graftlint: disable=purity`` on a ``def`` line exempts that function
entirely (host-side prep helpers that must never run under jit document
themselves this way); on a call or access line it suppresses that one
site and cuts the call edge.
"""

from __future__ import annotations

import ast
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, SourceFile, dotted_name

CHECK = "purity"

#: packages (relative to the scanned package root) the call graph spans
DEFAULT_SCOPE = ("ops", "models", "parallel")

_HOST_SYNC_ATTRS = {"block_until_ready", "item"}
_NUMPY_ALIASES = {"np", "numpy"}
_RANDOM_FNS = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "uniform", "gauss", "sample", "betavariate", "normalvariate",
}
_LOCK_ATTRS = {"_lock", "_mu", "_cond"}
_WALL_CLOCKS = {"time.time", "time.monotonic"}


class FuncInfo:
    def __init__(self, src: SourceFile, module: str, cls: Optional[str],
                 node: ast.FunctionDef):
        self.src = src
        self.module = module
        self.cls = cls
        self.node = node
        self.qual = (
            f"{module}:{cls}.{node.name}" if cls else f"{module}:{node.name}"
        )
        self.is_root = False
        self.exempt = src.suppressed(node.lineno, CHECK)
        self.calls: List[Tuple[int, str]] = []       # (line, callee qual)
        self.violations: List[Tuple[int, str]] = []  # (line, message)


def _in_scope(relpath: str, package: str, scope: Tuple[str, ...]) -> bool:
    parts = relpath.replace("\\", "/").split("/")
    return len(parts) >= 2 and parts[0] == package and parts[1] in scope


def _import_maps(src: SourceFile) -> Tuple[Dict[str, str], Dict[str, str]]:
    """(name -> defining module, alias -> module) from this module's
    imports, with relative imports resolved against the module path."""
    name_map: Dict[str, str] = {}
    alias_map: Dict[str, str] = {}
    mod_parts = src.module.split(".")
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                alias_map[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = mod_parts[: len(mod_parts) - node.level]
            else:
                base = []
            target = ".".join(base + (node.module or "").split("."))
            target = target.strip(".")
            for a in node.names:
                bound = a.asname or a.name
                # could be a symbol OR a submodule; record both guesses
                name_map[bound] = f"{target}.{a.name}" if target else a.name
                alias_map.setdefault(
                    bound, f"{target}.{a.name}" if target else a.name
                )
    return name_map, alias_map


def _is_hot_path_decorator(dec: ast.AST) -> bool:
    name = dotted_name(dec)
    return name is not None and name.split(".")[-1] == "hot_path"


def _collect_functions(
    files: List[SourceFile], package: str, scope: Tuple[str, ...]
) -> Dict[str, FuncInfo]:
    table: Dict[str, FuncInfo] = {}
    for src in files:
        if not _in_scope(src.relpath, package, scope):
            continue
        mod = src.module
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FuncInfo(src, mod, None, node)
                table[fi.qual] = fi
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fi = FuncInfo(src, mod, node.name, sub)
                        table[fi.qual] = fi
    for fi in table.values():
        fi.is_root = any(
            _is_hot_path_decorator(d) for d in fi.node.decorator_list
        )
    return table


def _analyze_function(
    fi: FuncInfo,
    table: Dict[str, FuncInfo],
    by_name: Dict[str, List[str]],
    name_map: Dict[str, str],
    alias_map: Dict[str, str],
) -> None:
    src, mod = fi.src, fi.module

    def resolve(call: ast.Call) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Name):
            # imported symbol, else same-module function
            target = name_map.get(fn.id)
            if target is not None:
                # target is "pkg.mod.sym"
                m, _, sym = target.rpartition(".")
                qual = f"{m}:{sym}"
                if qual in table:
                    return qual
            qual = f"{mod}:{fn.id}"
            if qual in table:
                return qual
            return None
        if isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name):
                if fn.value.id == "self" and fi.cls:
                    qual = f"{mod}:{fi.cls}.{fn.attr}"
                    if qual in table:
                        return qual
                target_mod = alias_map.get(fn.value.id)
                if target_mod is not None:
                    qual = f"{target_mod}:{fn.attr}"
                    if qual in table:
                        return qual
            cands = by_name.get(fn.attr, [])
            if len(cands) == 1:
                return cands[0]
        return None

    def flag(line: int, message: str) -> None:
        if not src.suppressed(line, CHECK):
            fi.violations.append((line, message))

    for node in ast.walk(fi.node):
        if isinstance(node, ast.With):
            for item in node.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Attribute) and ctx.attr in _LOCK_ATTRS:
                    flag(node.lineno, f"takes lock '.{ctx.attr}'")
        if not isinstance(node, ast.Call):
            continue
        line = node.lineno
        name = dotted_name(node.func)
        if name == "jax.device_get":
            flag(line, "jax.device_get (host sync)")
        elif name in _WALL_CLOCKS:
            flag(line, f"{name}() (wall clock on the hot path)")
        elif name is not None and name.split(".")[0] in _NUMPY_ALIASES and (
            name.split(".")[-1] in ("asarray", "array")
        ):
            flag(line, f"{name} (implicit device→host readback)")
        elif (
            name is not None
            and name.startswith("random.")
            and name.split(".")[-1] in _RANDOM_FNS
        ):
            flag(line, f"{name} (unseeded randomness)")
        elif isinstance(node.func, ast.Attribute):
            if node.func.attr in _HOST_SYNC_ATTRS:
                flag(line, f".{node.func.attr}() (host sync)")
            elif node.func.attr == "acquire":
                flag(line, ".acquire() (lock on the hot path)")
        elif isinstance(node.func, ast.Name) and node.func.id in ("float", "int"):
            if len(node.args) == 1 and any(
                isinstance(sub, (ast.Call, ast.Subscript))
                for sub in ast.walk(node.args[0])
            ):
                flag(
                    line,
                    f"{node.func.id}() on a computed value (tracer leak / "
                    "host sync)",
                )
        callee = resolve(node)
        if callee is not None and not src.suppressed(line, CHECK):
            fi.calls.append((line, callee))


def check(
    files: List[SourceFile],
    package: str = "kubernetes_tpu",
    scope: Tuple[str, ...] = DEFAULT_SCOPE,
) -> List[Finding]:
    table = _collect_functions(files, package, scope)
    by_name: Dict[str, List[str]] = {}
    for qual, fi in table.items():
        by_name.setdefault(fi.node.name, []).append(qual)
    maps_cache: Dict[str, Tuple[Dict[str, str], Dict[str, str]]] = {}
    for fi in table.values():
        if fi.exempt:
            continue
        if fi.src.relpath not in maps_cache:
            maps_cache[fi.src.relpath] = _import_maps(fi.src)
        name_map, alias_map = maps_cache[fi.src.relpath]
        _analyze_function(fi, table, by_name, name_map, alias_map)

    # BFS from the @hot_path roots; remember one witness path for messages
    reachable: Dict[str, str] = {}  # qual -> root qual
    parent: Dict[str, str] = {}
    q: deque = deque()
    for qual, fi in table.items():
        if fi.is_root and not fi.exempt:
            reachable[qual] = qual
            q.append(qual)
    while q:
        cur = q.popleft()
        for _, callee in table[cur].calls:
            if callee in reachable or table[callee].exempt:
                continue
            reachable[callee] = reachable[cur]
            parent[callee] = cur
            q.append(callee)

    findings: List[Finding] = []
    for qual in sorted(reachable):
        fi = table[qual]
        root = reachable[qual]
        for line, message in fi.violations:
            via = ""
            if root != qual:
                chain: List[str] = []
                cur = qual
                while cur != root and cur in parent:
                    cur = parent[cur]
                    chain.append(cur.split(":")[-1])
                via = f" (reached from @hot_path root '{root.split(':')[-1]}'" + (
                    f" via {' -> '.join(reversed(chain))})" if chain else ")"
                )
            findings.append(
                Finding(
                    CHECK, fi.src.relpath, line,
                    qual.split(":")[-1], message + via,
                )
            )
    return findings
