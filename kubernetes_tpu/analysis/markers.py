"""Markers the static analysis passes key off.

Import-light on purpose: ops/ modules tag their solve roots with
``@hot_path`` and must not drag anything beyond the stdlib in when they
do.  The purity pass (analysis/purity.py) matches the decorator by
NAME (``hot_path``, ``markers.hot_path``, ...), so the runtime effect
here is only an attribute for introspection/tests.
"""

from __future__ import annotations


def hot_path(fn):
    """Mark a function as a hot-path root: everything statically
    reachable from it must stay free of host syncs, tracer leaks, wall
    clocks, unseeded randomness, and locks (the purity pass walks the
    call graph from these roots)."""
    fn.__graftlint_hot_path__ = True
    return fn
