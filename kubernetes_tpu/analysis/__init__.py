"""graftlint — project-native static analysis for the scheduler tree.

Eight import-light passes (plus the JAX-backed ``--shapes`` mode)
enforce the conventions the solve→assume→bind pipeline's correctness
rests on (docs/static_analysis.md):

  guarded-by   fields declared guarded (``GUARDED_FIELDS`` class attr or
               a ``# guarded_by: _lock`` comment in ``__init__``) may
               only be touched inside ``with self.<lock>:`` or from a
               method reviewed to run with the lock held / before the
               object is shared (``LOCKED_METHODS``, ``_locked_*`` /
               ``*_locked`` names, ``__init__``).
  purity       functions reachable from ``@hot_path`` roots (the solve
               kernels and the dispatch path) must not host-sync
               (``jax.device_get`` / ``.block_until_ready()`` /
               ``np.asarray`` / ``.item()``), leak tracers through
               ``float()``/``int()``, read wall clocks, draw unseeded
               randomness, or take locks.
  registry     every ``faults.fire("p")`` site names a declared point in
               testing/faults.py and vice versa; every metric the
               scheduler Registry defines is exported by a
               perf/collectors.py surface and vice versa.
  lock-order   the static lock-acquisition graph (lock held → lock
               acquired) must be acyclic.  The runtime half lives in
               analysis/runtime.py.
  tensor-contract
               every NamedTuple array field in the ops tree carries a
               parseable ``# <dtype>[<axes>]`` contract; kernel code
               must stay dtype-stable (no 64-bit numpy values, no
               bare-int bitset shifts) and axis-consistent (a
               ``P``-derived variable must not index an ``N`` axis).
  atomicity    guarded accesses must COMPOSE: no check-then-act across
               a lock boundary (a guarded value captured under the lock
               then branched on / written back after release), no split
               read-modify-write (a compound guarded update spanning two
               ``with lock:`` sections of one method), and every
               ``Condition.wait`` sits in a while-predicate loop inside
               its ``with``.  The runtime complement is the interleaving
               explorer (analysis/interleave.py + analysis/scenarios.py).
  coherence    device-resident caches (``# resident:`` annotated fields
               — DeviceClusterMirror, PartialsCache) must implement the
               full discipline matrix: speculation_point/rollback/
               invalidate (+ verify or a declared oracle twin), a
               registered fault point and chaos-seed family, all-
               residents parity at every bookmark/rollback/invalidate
               choke point, no direct resident-field reads from
               ``@hot_path`` code, and per-solve prep rebuilds declared
               ``# coherence: rebuilt-per-solve``.  The runtime half is
               the GRAFTLINT_COHERENCE=1 epoch auditor
               (analysis/epochs.py).
  obligations  linear obligations: a resource acquired on one line
               (popped pod, DispatchArbiter slot, APF seat, cache
               assume, ``*_inflight`` increment, armed fault registry)
               must be discharged exactly once on every outgoing path
               — including exception edges and ``finally`` blocks —
               with call-summary propagation so discharge via a helper
               (``_fail_bind``, ``_salvage_cycle``, ``release_slot``)
               counts, and ownership transfer (return / attribute
               store / hand-off callee) discharging without a local
               release.  The runtime half is the
               GRAFTLINT_OBLIGATIONS=1 exactly-once ledger
               (analysis/ledger.py).
  recompile-discipline
               (``--shapes`` mode / ``make lint-shapes``: imports JAX)
               every @hot_path kernel driven through ``jax.eval_shape``
               across the pad-bucket lattice must produce outputs
               matching the contracts, and the encoder must land
               exactly on the lattice — no argument can trigger an
               unexpected XLA retrace.  The runtime half is the
               GRAFTLINT_SHAPES=1 retrace tracker (analysis/retrace.py).

Escape hatch: ``# graftlint: disable=<check>[,<check>...]`` on the
offending line (or on a ``def`` line to exempt a whole function from
the purity walk).  Grandfathered findings live in ``baseline.json``
next to this file; the CLI fails on findings outside the baseline AND
on stale baseline entries, so the baseline can only shrink.

This package is import-light on purpose (stdlib ``ast`` only): ``make
lint`` must run without initializing JAX.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: every check id the suppression syntax accepts.  The first eight run
#: in the default import-light CLI; "recompile-discipline" imports JAX
#: and runs only under `python -m kubernetes_tpu.analysis --shapes`.
CHECK_IDS = (
    "guarded-by", "purity", "registry", "lock-order", "tensor-contract",
    "atomicity", "coherence", "obligations", "recompile-discipline",
)

#: the stdlib-ast subset run_all executes (no JAX initialization)
STATIC_CHECK_IDS = (
    "guarded-by", "purity", "registry", "lock-order", "tensor-contract",
    "atomicity", "coherence", "obligations",
)

# check ids after `disable=`, comma-separated; anything after the ids
# (conventionally ` -- <justification>`) is free text
_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([\w-]+(?:\s*,\s*[\w-]+)*)")


@dataclass(frozen=True)
class Finding:
    check: str     # one of CHECK_IDS
    file: str      # path relative to the scanned root
    line: int      # 1-based; informational only (baseline keys skip it)
    symbol: str    # "Class.method", "function", or the drifted name
    message: str

    def key(self) -> Tuple[str, str, str, str]:
        """Line-number-independent identity used by the baseline, so an
        unrelated edit above a grandfathered finding doesn't un-baseline
        it."""
        return (self.check, self.file, self.symbol, self.message)

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.check}] {self.symbol}: {self.message}"


class SourceFile:
    """One parsed module: AST + per-line suppression sets."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=relpath)
        # 1-based line -> set of suppressed check ids ("all" wildcards)
        self.suppress: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self.suppress[i] = {
                    c.strip() for c in m.group(1).split(",") if c.strip()
                }

    def suppressed(self, line: int, check: str) -> bool:
        s = self.suppress.get(line)
        return s is not None and (check in s or "all" in s)

    # module name relative to the scan root, e.g. "kubernetes_tpu.ops.assign"
    @property
    def module(self) -> str:
        mod = self.relpath[:-3] if self.relpath.endswith(".py") else self.relpath
        mod = mod.replace(os.sep, ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        return mod


def load_sources(
    root: str, subdirs: Optional[Sequence[str]] = None
) -> List[SourceFile]:
    """Parse every .py file under root (or root/<subdir> for each given
    subdir).  Unparseable files are skipped — the interpreter and tier-1
    tests own syntax errors; graftlint owns semantics."""
    out: List[SourceFile] = []
    bases = [os.path.join(root, s) for s in subdirs] if subdirs else [root]
    for base in bases:
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [
                d for d in dirnames if d != "__pycache__" and not d.startswith(".")
            ]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root)
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        text = f.read()
                    out.append(SourceFile(path, rel, text))
                except (SyntaxError, UnicodeDecodeError, OSError):
                    continue
    return out


# -- shared AST helpers ------------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_constants(node: ast.AST) -> List[str]:
    """Every string literal inside a set/tuple/list/dict-key literal."""
    out: List[str] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.append(sub.value)
    return out


# -- runner ------------------------------------------------------------------

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, list):
        raise ValueError(f"baseline {path}: expected a JSON list")
    return data


def save_baseline(path: str, findings: Iterable[Finding]) -> None:
    data = [
        {
            "check": f.check,
            "file": f.file,
            "symbol": f.symbol,
            "message": f.message,
        }
        for f in findings
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: Sequence[dict]
) -> Tuple[List[Finding], List[dict]]:
    """(new findings, stale baseline entries).  A baseline entry matches
    at most once, so duplicated findings surface past a single
    grandfathered instance."""
    pool: Dict[Tuple[str, str, str, str], int] = {}
    for entry in baseline:
        key = (
            entry.get("check", ""),
            entry.get("file", ""),
            entry.get("symbol", ""),
            entry.get("message", ""),
        )
        pool[key] = pool.get(key, 0) + 1
    new: List[Finding] = []
    for f in findings:
        k = f.key()
        if pool.get(k, 0) > 0:
            pool[k] -= 1
        else:
            new.append(f)
    stale = []
    for entry in baseline:
        key = (
            entry.get("check", ""),
            entry.get("file", ""),
            entry.get("symbol", ""),
            entry.get("message", ""),
        )
        if pool.get(key, 0) > 0:
            pool[key] -= 1
            stale.append(entry)
    return new, stale


def run_all(
    root: str,
    checks: Optional[Sequence[str]] = None,
    package: str = "kubernetes_tpu",
) -> List[Finding]:
    """Run the selected static passes (default: all eight import-light
    checks) over root/<package>.  The JAX-backed recompile-discipline
    pass is NOT run here — it lives behind the CLI's ``--shapes`` mode
    (analysis/shapes.py) so ``make lint`` stays import-light."""
    from . import (
        atomicity, coherence, guarded, lockorder, obligations, purity,
        registry, tensorcontract,
    )

    files = load_sources(root, [package])
    selected = set(checks or STATIC_CHECK_IDS)
    findings: List[Finding] = []
    if "guarded-by" in selected:
        findings.extend(guarded.check(files))
    if "purity" in selected:
        findings.extend(purity.check(files))
    if "registry" in selected:
        findings.extend(registry.check(files))
    if "lock-order" in selected:
        findings.extend(lockorder.check(files))
    if "tensor-contract" in selected:
        findings.extend(tensorcontract.check(files))
    if "atomicity" in selected:
        findings.extend(atomicity.check(files))
    if "coherence" in selected:
        findings.extend(coherence.check(files))
    if "obligations" in selected:
        findings.extend(obligations.check(files))
    findings.sort(key=lambda f: (f.file, f.line, f.check, f.message))
    return findings
