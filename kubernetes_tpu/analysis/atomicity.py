"""atomicity: lock-ATOMICITY discipline for annotated shared state.

The guarded-by pass proves every touch of a ``GUARDED_FIELDS`` field
happens under its lock; this pass proves the touches COMPOSE correctly.
Holding the lock for each individual access is not enough when a
decision spans a release: the classic TOCTOU shapes are invisible to
guarded-by because every single access is locked.  Three rules:

check-then-act
    A guarded value is captured into a local under ``with self.<lock>:``
    and, after the block ends, the local is branched on (``if``/``while``
    test) or written back into a guarded field while the lock is no
    longer held.  Between release and use any other thread may have
    changed the field — the branch decides on stale state.  Fix: widen
    the critical section, or re-read the field under the lock before
    acting (re-assigning the local from ``self.<field>`` under a later
    ``with self.<lock>:`` clears the capture).

split-rmw
    The same capture-then-write-back shape, but the write-back happens
    under a SECOND ``with self.<lock>:`` section of the same method — a
    compound read-modify-write split across two critical sections.  The
    update is lost if another thread wrote between the sections.  Fix:
    one critical section, or recompute from the field inside the second.

cv-wait-without-predicate-loop
    ``<cv>.wait(...)`` inside ``with <cv>:`` but not inside a ``while``
    loop WITHIN that with-block.  Condition waits can wake spuriously,
    on a broadcast meant for someone else, or via timeout — the
    predicate must be re-checked under the SAME lock acquisition before
    acting (an outer loop that re-enters the with-block re-checks under
    a fresh acquisition, which leaves an act-on-stale-wake window inside
    the first; docs/static_analysis.md shows the rewrite).
    ``wait_for`` loops internally and never flags.  This rule needs no
    ``GUARDED_FIELDS`` declaration — it applies to every with+wait pair
    in the tree.

Escape hatch: ``# graftlint: disable=atomicity -- <why>`` on the USE
line (the branch / write-back / wait), like every other pass.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, SourceFile, dotted_name
from .guarded import _class_decls, _method_exempt, _with_locks

CHECK = "atomicity"


@dataclass
class _Capture:
    """A local holding a guarded value: ``x = ...self.<field>...`` under
    ``with self.<lock>:``."""

    var: str
    field: str
    lock: str
    line: int
    with_id: int   # id() of the With node the capture happened under


def _guarded_reads(
    node: ast.AST, guarded: Dict[str, str], held: Set[str]
) -> List[str]:
    """Guarded fields read by this expression whose lock is held."""
    out: List[str] = []
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "self"
            and sub.attr in guarded
            and guarded[sub.attr] in held
        ):
            out.append(sub.attr)
    return out


def _names_in(node: ast.AST) -> Set[str]:
    return {
        sub.id for sub in ast.walk(node)
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
    }


class _MethodChecker:
    """Walks one method in source order, tracking lock context and
    captured guarded values, emitting check-then-act / split-rmw
    findings at use sites."""

    def __init__(
        self,
        src: SourceFile,
        symbol: str,
        guarded: Dict[str, str],
        findings: List[Finding],
    ):
        self.src = src
        self.symbol = symbol
        self.guarded = guarded
        self.findings = findings
        self.captures: Dict[str, _Capture] = {}

    # -- capture bookkeeping -----------------------------------------------

    def _assign(
        self, stmt: ast.Assign, held: Set[str], with_id: int
    ) -> None:
        if len(stmt.targets) != 1 or not isinstance(stmt.targets[0], ast.Name):
            # tuple unpacks / attribute targets: not the capture shape
            for tgt in stmt.targets:
                for name in ast.walk(tgt):
                    if isinstance(name, ast.Name):
                        self.captures.pop(name.id, None)
            return
        var = stmt.targets[0].id
        reads = _guarded_reads(stmt.value, self.guarded, held)
        if reads and with_id:
            self.captures[var] = _Capture(
                var, reads[0], self.guarded[reads[0]], stmt.lineno, with_id
            )
        else:
            # reassigned from something else (or outside any lock):
            # the local no longer tracks the guarded field
            self.captures.pop(var, None)

    # -- use sites ---------------------------------------------------------

    def _flag(self, cap: _Capture, line: int, kind: str, what: str) -> None:
        if self.src.suppressed(line, CHECK):
            return
        if kind == "split-rmw":
            msg = (
                f"split read-modify-write: '{cap.var}' captured from "
                f"guarded field '{cap.field}' under 'with self.{cap.lock}' "
                f"(line {cap.line}) is {what} under a separate "
                f"'with self.{cap.lock}' section — the compound update is "
                "lost if another thread wrote between the sections"
            )
        else:
            msg = (
                f"check-then-act across a lock boundary: '{cap.var}' "
                f"captured from guarded field '{cap.field}' under "
                f"'with self.{cap.lock}' (line {cap.line}) is {what} after "
                "the lock was released, without revalidation"
            )
        self.findings.append(
            Finding(CHECK, self.src.relpath, line, self.symbol, msg)
        )

    def _check_use(
        self,
        names: Set[str],
        line: int,
        held: Set[str],
        with_id: int,
        what: str,
        write_back: bool,
    ) -> None:
        for var in sorted(names & set(self.captures)):
            cap = self.captures[var]
            if with_id == cap.with_id:
                continue  # same critical section: atomic
            if cap.lock in held:
                # re-locked in a different section: a branch here re-runs
                # under the lock against live state unless it consults
                # the stale capture for a WRITE — that's the split-RMW
                # shape; branch-only re-locked uses stay quiet (the
                # second section revalidates by construction when it
                # re-reads the field, and flagging every metrics-style
                # carry-over would drown the signal)
                if write_back:
                    self._flag(cap, line, "split-rmw", what)
                    self.captures.pop(var, None)
            else:
                self._flag(cap, line, "check-then-act", what)
                self.captures.pop(var, None)

    # -- the walk ----------------------------------------------------------

    def visit_block(
        self, body: List[ast.stmt], held: Set[str], with_id: int
    ) -> None:
        for stmt in body:
            self.visit_stmt(stmt, held, with_id)

    def visit_stmt(self, stmt: ast.stmt, held: Set[str], with_id: int) -> None:
        if isinstance(stmt, ast.With):
            locks = {
                a for a in _with_locks(stmt)
                if a in set(self.guarded.values())
            }
            inner_id = id(stmt) if locks else with_id
            self.visit_block(stmt.body, held | locks, inner_id)
            return
        if isinstance(stmt, ast.Assign):
            # write-back to a guarded field using a stale capture?
            for tgt in stmt.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and tgt.attr in self.guarded
                ):
                    self._check_use(
                        _names_in(stmt.value), stmt.lineno, held, with_id,
                        f"written back into guarded field '{tgt.attr}'",
                        write_back=True,
                    )
            self._assign(stmt, held, with_id)
            return
        if isinstance(stmt, ast.AugAssign):
            tgt = stmt.target
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
                and tgt.attr in self.guarded
            ):
                self._check_use(
                    _names_in(stmt.value), stmt.lineno, held, with_id,
                    f"written back into guarded field '{tgt.attr}'",
                    write_back=True,
                )
            elif isinstance(tgt, ast.Name):
                self.captures.pop(tgt.id, None)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._check_use(
                _names_in(stmt.test), stmt.lineno, held, with_id,
                "branched on", write_back=False,
            )
            self.visit_block(stmt.body, held, with_id)
            self.visit_block(stmt.orelse, held, with_id)
            return
        if isinstance(stmt, ast.For):
            self.visit_block(stmt.body, held, with_id)
            self.visit_block(stmt.orelse, held, with_id)
            return
        if isinstance(stmt, ast.Try):
            self.visit_block(stmt.body, held, with_id)
            for h in stmt.handlers:
                self.visit_block(h.body, held, with_id)
            self.visit_block(stmt.orelse, held, with_id)
            self.visit_block(stmt.finalbody, held, with_id)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def captures by reference at CALL time — beyond
            # this lexical pass; clear anything it rebinds and move on
            return
        # other statements (Expr, Return, Raise, ...): no branch, no
        # write-back — a plain read of a stale local (logging, metrics,
        # return values) is not an atomicity decision


def _check_methods(src: SourceFile, findings: List[Finding]) -> None:
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        guarded, locked_methods = _class_decls(src, node)
        if not guarded:
            continue
        for stmt in node.body:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and not _method_exempt(stmt.name, locked_methods):
                checker = _MethodChecker(
                    src, f"{node.name}.{stmt.name}", guarded, findings
                )
                checker.visit_block(stmt.body, set(), 0)


# -- cv-wait-without-predicate-loop ------------------------------------------


def _check_cv_waits(src: SourceFile, findings: List[Finding]) -> None:
    """For every ``with E:`` block, a ``E.wait(...)`` inside it must sit
    under a ``while`` that is itself inside the with-block."""

    def fn_symbol(stack: List[str]) -> str:
        return ".".join(stack) or src.module

    def walk(node: ast.AST, stack: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, stack + [child.name])
            elif isinstance(child, ast.ClassDef):
                walk(child, stack + [child.name])
            else:
                if isinstance(child, ast.With):
                    for item in child.items:
                        cv = dotted_name(item.context_expr)
                        if cv is not None:
                            _scan_with(child, cv, stack)
                walk(child, stack)

    def _scan_with(with_node: ast.With, cv: str, stack: List[str]) -> None:
        def scan(node: ast.AST, in_while: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return  # nested defs run later, elsewhere
            if isinstance(node, ast.With) and any(
                dotted_name(i.context_expr) == cv for i in node.items
            ):
                # reentrant re-acquisition of the same cv: the outer
                # walk scans that block as its own root
                return
            if isinstance(node, ast.While):
                for child in ast.iter_child_nodes(node):
                    scan(child, True)
                return
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "wait"
                and dotted_name(node.func.value) == cv
                and not in_while
                and not src.suppressed(node.lineno, CHECK)
            ):
                findings.append(
                    Finding(
                        CHECK, src.relpath, node.lineno,
                        fn_symbol(stack),
                        f"'{cv}.wait(...)' is not inside a while-"
                        f"predicate loop within 'with {cv}:' — a "
                        "spurious or stolen wakeup acts without "
                        "re-checking the predicate under this "
                        "acquisition",
                    )
                )
                return
            for child in ast.iter_child_nodes(node):
                scan(child, in_while)

        for stmt in with_node.body:
            scan(stmt, False)

    walk(src.tree, [])


def check(files: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in files:
        _check_methods(src, findings)
        _check_cv_waits(src, findings)
    return findings
