"""Runtime exactly-once obligation ledger — the dynamic half of
graftobl (obligations).

The static pass (analysis/obligations.py) proves every acquisition
site is structurally paired with a discharge on every outgoing path.
This ledger observes the acquisitions that ACTUALLY happen and answers
the question the structural proof cannot: did each individual object
reach exactly one disposition by quiesce time?

Tracked obligation kinds (hooks live next to the production guards, so
a legitimately-idempotent second call never reaches the ledger):

  pod                a pod popped into the queue's "inflight" tier
                     (scheduler/queue.py take()) must leave it exactly
                     once — done / delete / requeue_backoff /
                     add_unschedulable / re-gate.
  assume             a cache.assume() insert must be confirmed
                     (add_pod/finish_binding) or forgotten
                     (forget/forget_key/remove_*/cleanup_expired)
                     exactly once (scheduler/cache.py).
  seat               an APF Seat granted by APFGate.acquire() must be
                     released exactly once (api/flowcontrol.py — the
                     hook fires after the ``seat._released`` guard, so
                     the deliberate idempotence of Seat.release never
                     counts as a double-discharge).
  slot               a DispatchArbiter admission (counter, owner-scoped
                     per arbiter).  release() reports to the ledger
                     BEFORE the below-zero swallow guard, so a masked
                     double-release surfaces here even though the
                     production counter is protected.
  stream_inflight    scheduler._stream_inflight increments (counter,
                     owner-scoped per scheduler).
  dispatch_inflight  store shard _dispatch_inflight arm/clear (counter,
                     owner-scoped per shard).
  fault              testing/faults.py arm() → disarm() in tests.

Keyed kinds record per-object acquire/discharge transitions with a
short acquiring call chain; discharging an already-discharged key
raises :class:`ObligationViolation` IMMEDIATELY (a double-disposition
is corruption in progress, not an end-state anomaly).  Counter kinds
keep an owner-scoped LIFO of acquire chains; popping an empty stack
for a known owner is likewise a double-discharge.  Keys and owners the
ledger never saw acquired are ignored silently — arming mid-flight
(a session fixture around an already-warm process) must not
misattribute pre-arming acquisitions.

At quiesce, :meth:`ObligationLedger.assert_clean` reports every leaked
obligation with the call chain that acquired it — turning the chaos
suites' "assume set empty / all pods bound" end-state assertions into
per-object causal traces (tests/test_chaos.py quiesce blocks call
:meth:`assert_quiesced` with the kinds that must have drained).

Usage (scoped, mirroring analysis/epochs.py)::

    from kubernetes_tpu.analysis import ledger

    with ledger.tracked() as led:
        ...                      # scheduler runs, hooks record
    led.assert_clean()

Under pytest, set ``GRAFTLINT_OBLIGATIONS=1`` to arm the ledger for
the whole session (tests/conftest.py wires the fixture, exactly like
GRAFTLINT_COHERENCE); bench.py arms it per run and ``BENCH_STRICT=1``
fails on any leak or double-discharge.  The scheduler mirrors
:func:`tracked_total` / :func:`leaks_total` /
:func:`double_discharge_total` into the
``scheduler_obligations_tracked_total`` /
``scheduler_obligation_leaks_total`` /
``scheduler_obligation_double_discharge_total`` gauges each cycle.

This module is import-light (stdlib only): hooks cost one module-global
None check when disarmed.
"""

from __future__ import annotations

import contextlib
import sys
import threading
from typing import Dict, List, Optional, Tuple

#: kinds tracked per-object (acquire/discharge keyed by object identity)
KEYED_KINDS = ("pod", "assume", "seat", "fault")

#: kinds tracked as owner-scoped counters (LIFO stack of acquire chains)
COUNTER_KINDS = ("slot", "stream_inflight", "dispatch_inflight")


class ObligationViolation(AssertionError):
    """An obligation was discharged twice, or leaked past quiesce."""


def _chain(skip: int = 2, limit: int = 7) -> str:
    """A short acquiring call chain: the last few frames below the
    ledger method (skip drops _chain + the method itself), rendered
    one-per-segment ("file:line fn").  A raw ``sys._getframe`` walk,
    not traceback.extract_stack — the extract path reads source lines
    through linecache per frame, and this runs on every pod pop/assume
    of an armed run (the hooks must not perturb the overlap timing the
    chaos suites assert on)."""
    try:
        f = sys._getframe(skip)
    except ValueError:  # shallower stack than skip
        return "<top>"
    parts: List[str] = []
    while f is not None and len(parts) < limit:
        code = f.f_code
        parts.append(
            f"{code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno} "
            f"{code.co_name}"
        )
        f = f.f_back
    return " <- ".join(parts)


class ObligationLedger:
    def __init__(self):
        self._mu = threading.Lock()
        self.acquired = 0
        # keyed kinds: (kind, key) -> acquiring chain while HELD,
        # then moved to _done with the discharging chain
        self._held: Dict[Tuple[str, object], str] = {}
        self._done: Dict[Tuple[str, object], str] = {}
        # counter kinds: (kind, owner) -> LIFO of acquiring chains;
        # owners stay in the dict after draining so an extra pop is
        # distinguishable from a never-seen owner
        self._stacks: Dict[Tuple[str, object], List[str]] = {}
        self.double: List[str] = []

    # -- keyed kinds ---------------------------------------------------------

    def acquire(self, kind: str, key: object) -> None:
        with self._mu:
            self.acquired += 1
            k = (kind, key)
            # a re-acquire retires the previous cycle of this key (a
            # requeued pod popped again, a re-assume after forget)
            self._done.pop(k, None)
            self._held[k] = _chain()

    def discharge(self, kind: str, key: object) -> None:
        with self._mu:
            k = (kind, key)
            chain = self._held.pop(k, None)
            if chain is not None:
                self._done[k] = _chain()
                return
            prev = self._done.get(k)
            if prev is None:
                return  # never saw the acquire (armed mid-flight)
            msg = (
                f"double-discharge of {kind} {key!r}: already discharged"
                f" at [{prev}], discharged again at [{_chain()}]"
            )
            self.double.append(msg)
        raise ObligationViolation(msg)

    # -- counter kinds -------------------------------------------------------

    def push(self, kind: str, owner: object) -> None:
        with self._mu:
            self.acquired += 1
            self._stacks.setdefault((kind, owner), []).append(_chain())

    def pop(self, kind: str, owner: object) -> None:
        with self._mu:
            stack = self._stacks.get((kind, owner))
            if stack is None:
                return  # never saw an acquire for this owner
            if stack:
                stack.pop()
                return
            msg = (
                f"double-discharge of {kind} counter (owner {owner:#x}): "
                f"released below zero at [{_chain()}]"
            )
            self.double.append(msg)
        raise ObligationViolation(msg)

    def reset_cycles(self) -> None:
        """Forget completed acquire/discharge cycles.  Keyed kinds use
        identity-stable keys (pod keys, object ids) that RECUR across
        tests in a session-armed run — a retired ``default/p3`` from
        one test must not make the next test's discharge-without-
        acquire of its own ``default/p3`` (an informer delete of a
        never-assumed pod) read as a double-discharge.  The per-test
        conftest fixture calls this at every test boundary; held
        obligations and recorded violations survive — only the
        double-discharge lookback window resets."""
        with self._mu:
            self._done.clear()

    def abandon(self) -> None:
        """Process-death semantics: drop every held obligation and
        counter stack without counting a discharge.  Scheduler.kill()
        (the chaos harness's SIGKILL analogue) calls this — a real
        crash takes the in-memory ledger with it, and the abandoned
        pods/assumes are recovered by TTL expiry and successor
        reconciliation, not by structural discharge.  Keys stay out of
        ``_done`` so a successor's re-acquire/discharge of the same
        pod key is a fresh cycle, and a stray late discharge from a
        half-dead thread reads as never-seen (silent) — which is why
        the counter OWNERS are forgotten outright (an empty-but-known
        stack means double-discharge) and the ``_done`` lookback is
        dropped (kill() shuts the commit pool down without waiting, so
        an in-flight hand-off may discharge after the abandon).  The
        cost: a concurrent live instance's held obligations are
        dropped too — acceptable in crash tests, which re-verify
        drainage on the survivor afterwards."""
        with self._mu:
            self._held.clear()
            self._done.clear()
            self._stacks.clear()

    # -- results -------------------------------------------------------------

    def outstanding(self, kinds: Optional[Tuple[str, ...]] = None) -> List[str]:
        """Leaked obligations (acquired, never discharged), each with
        its acquiring call chain."""
        with self._mu:
            out = [
                f"leaked {kind} {key!r}: acquired at [{chain}], never"
                " discharged"
                for (kind, key), chain in sorted(
                    self._held.items(), key=lambda kv: repr(kv[0])
                )
                if kinds is None or kind in kinds
            ]
            for (kind, owner), stack in sorted(
                self._stacks.items(), key=lambda kv: repr(kv[0])
            ):
                if kinds is not None and kind not in kinds:
                    continue
                for chain in stack:
                    out.append(
                        f"leaked {kind} counter (owner {owner:#x}):"
                        f" acquired at [{chain}], never released"
                    )
            return out

    @property
    def tracked_total(self) -> int:
        with self._mu:
            return self.acquired

    @property
    def leaks_total(self) -> int:
        return len(self.outstanding())

    @property
    def double_discharge_total(self) -> int:
        with self._mu:
            return len(self.double)

    def assert_quiesced(self, kinds: Tuple[str, ...], context: str = "") -> None:
        """Quiesce-time check for the given kinds only: the chaos
        suites call this where they already assert assumed_count()==0 /
        all-bound, so a failure names the leaking acquisition site."""
        leaks = self.outstanding(kinds)
        if leaks:
            where = f" [{context}]" if context else ""
            raise ObligationViolation(
                f"{len(leaks)} obligation(s) leaked at quiesce{where}:\n"
                + "\n".join(leaks[:20])
            )

    def assert_clean(self) -> None:
        problems = list(self.double) + self.outstanding()
        if problems:
            raise ObligationViolation("\n".join(problems[:20]))


_active: Optional[ObligationLedger] = None


@contextlib.contextmanager
def tracked(led: Optional[ObligationLedger] = None):
    """Arm obligation tracking for the dynamic extent of the context.
    Nested arming shares the outer ledger (session fixture + per-test
    use must not shadow each other — analysis/epochs.py, same)."""
    global _active
    if _active is not None:
        yield _active
        return
    led = led or ObligationLedger()
    _active = led
    try:
        yield led
    finally:
        _active = None


def active() -> Optional[ObligationLedger]:
    return _active


# -- module-level hooks (no-ops unless armed) --------------------------------

def acquire(kind: str, key: object) -> None:
    a = _active
    if a is not None:
        a.acquire(kind, key)


def discharge(kind: str, key: object) -> None:
    a = _active
    if a is not None:
        a.discharge(kind, key)


def push(kind: str, owner: object) -> None:
    a = _active
    if a is not None:
        a.push(kind, owner)


def pop(kind: str, owner: object) -> None:
    a = _active
    if a is not None:
        a.pop(kind, owner)


def abandon() -> None:
    a = _active
    if a is not None:
        a.abandon()


def tracked_total() -> int:
    a = _active
    return a.tracked_total if a is not None else 0


def leaks_total() -> int:
    a = _active
    return a.leaks_total if a is not None else 0


def double_discharge_total() -> int:
    a = _active
    return a.double_discharge_total if a is not None else 0
