"""lock-order: the static lock-acquisition graph must be acyclic.

A lock identity is ``Class.attr`` for every ``self.<attr> =
threading.Lock()/RLock()/Condition()`` assignment found in the tree.
For every function the pass records which locks it acquires directly
(``with self.<attr>:``) and which calls it makes while holding one;
call edges resolve conservatively (same class via ``self.``, imported
names, module aliases, and otherwise only method names defined exactly
once across the tree — ambiguous names are skipped rather than
over-approximated into false cycles).  A fixpoint propagates the
"eventually acquires" set through the call graph, then every held →
acquired pair becomes an edge and cycles are reported.

Self-edges (re-acquiring the lock you hold) are ignored: the project's
shared locks are RLock/Condition and reentrancy is an explicit design
choice (admission under the store lock).

``# graftlint: disable=lock-order`` on a ``with`` or call line drops
that acquisition/edge from the graph.

The runtime complement (analysis/runtime.py) records ACTUAL acquisition
edges under pytest and fails on inversion — the static pass proves the
absence of cycles the resolver can see; the tracker catches the ones it
cannot.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from . import Finding, SourceFile, dotted_name

CHECK = "lock-order"

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
}


class _Fn:
    def __init__(self, src: SourceFile, module: str, cls: Optional[str],
                 node: ast.FunctionDef):
        self.src = src
        self.module = module
        self.cls = cls
        self.node = node
        self.qual = (
            f"{module}:{cls}.{node.name}" if cls else f"{module}:{node.name}"
        )
        self.acquires: Set[str] = set()          # locks taken anywhere in fn
        # (held lock, acquired lock, line) for direct nesting
        self.direct_edges: Set[Tuple[str, str, int]] = set()
        # (held locks, callee qual, line) for calls made under a lock,
        # plus lock-free calls (held == frozenset()) for ACQ propagation
        self.calls: List[Tuple[FrozenSet[str], str, int]] = []


def _lock_attrs(files: List[SourceFile]) -> Dict[str, Set[str]]:
    """class name -> lock attribute names (from self.<x> = threading.*())."""
    out: Dict[str, Set[str]] = {}
    for src in files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            attrs: Set[str] = set()
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Assign)
                    and isinstance(sub.value, ast.Call)
                    and dotted_name(sub.value.func) in _LOCK_CTORS
                ):
                    for tgt in sub.targets:
                        if (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                        ):
                            attrs.add(tgt.attr)
            if attrs:
                out.setdefault(node.name, set()).update(attrs)
    return out


def _collect(files: List[SourceFile]) -> Dict[str, _Fn]:
    table: Dict[str, _Fn] = {}
    for src in files:
        mod = src.module
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = _Fn(src, mod, None, node)
                table[fn.qual] = fn
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fn = _Fn(src, mod, node.name, sub)
                        table[fn.qual] = fn
    return table


def _import_maps(src: SourceFile) -> Tuple[Dict[str, str], Dict[str, str]]:
    from .purity import _import_maps as impl

    return impl(src)


def _analyze(
    fn: _Fn,
    table: Dict[str, _Fn],
    by_name: Dict[str, List[str]],
    locks_by_class: Dict[str, Set[str]],
    name_map: Dict[str, str],
    alias_map: Dict[str, str],
) -> None:
    src = fn.src
    own_locks = locks_by_class.get(fn.cls or "", set())

    def resolve(call: ast.Call) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):
            target = name_map.get(f.id)
            if target is not None:
                m, _, sym = target.rpartition(".")
                qual = f"{m}:{sym}"
                if qual in table:
                    return qual
            qual = f"{fn.module}:{f.id}"
            return qual if qual in table else None
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name):
                if f.value.id == "self" and fn.cls:
                    qual = f"{fn.module}:{fn.cls}.{f.attr}"
                    if qual in table:
                        return qual
                target_mod = alias_map.get(f.value.id)
                if target_mod is not None:
                    qual = f"{target_mod}:{f.attr}"
                    if qual in table:
                        return qual
            cands = by_name.get(f.attr, [])
            if len(cands) == 1:
                return cands[0]
        return None

    def visit(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, ast.With):
            acquired: Set[str] = set()
            for item in node.items:
                ctx = item.context_expr
                if (
                    isinstance(ctx, ast.Attribute)
                    and isinstance(ctx.value, ast.Name)
                    and ctx.value.id == "self"
                    and ctx.attr in own_locks
                    and not src.suppressed(node.lineno, CHECK)
                ):
                    lock = f"{fn.cls}.{ctx.attr}"
                    acquired.add(lock)
                    fn.acquires.add(lock)
                    for h in held:
                        if h != lock:
                            fn.direct_edges.add((h, lock, node.lineno))
                visit(item.context_expr, held)
            held = held | acquired
            for stmt in node.body:
                visit(stmt, held)
            return
        if isinstance(node, ast.Call):
            callee = resolve(node)
            if callee is not None and not src.suppressed(node.lineno, CHECK):
                fn.calls.append((held, callee, node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.node.body:
        visit(stmt, frozenset())


def build_graph(
    files: List[SourceFile],
) -> Tuple[Dict[str, Set[str]], Dict[Tuple[str, str], Tuple[str, int, str]]]:
    """(adjacency, edge -> one (file, line, function) witness site)."""
    locks_by_class = _lock_attrs(files)
    table = _collect(files)
    by_name: Dict[str, List[str]] = {}
    for qual, fn in table.items():
        by_name.setdefault(fn.node.name, []).append(qual)
    maps_cache: Dict[str, Tuple[Dict[str, str], Dict[str, str]]] = {}
    for fn in table.values():
        if fn.src.relpath not in maps_cache:
            maps_cache[fn.src.relpath] = _import_maps(fn.src)
        name_map, alias_map = maps_cache[fn.src.relpath]
        _analyze(fn, table, by_name, locks_by_class, name_map, alias_map)

    # fixpoint: ACQ(fn) = direct ∪ ⋃ ACQ(callee)
    acq: Dict[str, Set[str]] = {q: set(f.acquires) for q, f in table.items()}
    changed = True
    while changed:
        changed = False
        for qual, fn in table.items():
            cur = acq[qual]
            before = len(cur)
            for _, callee, _ in fn.calls:
                cur |= acq.get(callee, set())
            if len(cur) != before:
                changed = True

    adj: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for qual, fn in table.items():
        for a, b, line in fn.direct_edges:
            adj.setdefault(a, set()).add(b)
            sites.setdefault((a, b), (fn.src.relpath, line, qual))
        for held, callee, line in fn.calls:
            if not held:
                continue
            for b in acq.get(callee, ()):  # transitive acquisitions
                for a in held:
                    if a != b:
                        adj.setdefault(a, set()).add(b)
                        sites.setdefault(
                            (a, b), (fn.src.relpath, line, qual)
                        )
    return adj, sites


def _find_cycles(adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles via DFS; each cycle reported once (canonical
    rotation)."""
    cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str], seen: Set[str]) -> None:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start:
                cyc = path[:]
                pivot = cyc.index(min(cyc))
                cycles.add(tuple(cyc[pivot:] + cyc[:pivot]))
            elif nxt not in seen and nxt > start:
                # only explore nodes >= start: each cycle found from its
                # smallest node, bounding the search
                dfs(start, nxt, path + [nxt], seen | {nxt})

    for node in sorted(adj):
        dfs(node, node, [node], {node})
    return [list(c) for c in sorted(cycles)]


def check(files: List[SourceFile]) -> List[Finding]:
    adj, sites = build_graph(files)
    findings: List[Finding] = []
    for cycle in _find_cycles(adj):
        edges = list(zip(cycle, cycle[1:] + cycle[:1]))
        where = sites.get(edges[0], ("<unknown>", 1, "?"))
        detail = "; ".join(
            f"{a}->{b} at {sites.get((a, b), ('?', 0, '?'))[0]}:"
            f"{sites.get((a, b), ('?', 0, '?'))[1]}"
            for a, b in edges
        )
        findings.append(
            Finding(
                CHECK, where[0], where[1],
                " -> ".join(cycle + [cycle[0]]),
                f"lock-order cycle: {detail}",
            )
        )
    return findings
