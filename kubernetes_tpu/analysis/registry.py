"""registry: stringly-typed registries must not drift.

Two surfaces are reconciled:

fault points — every ``faults.fire("<point>")`` site in the tree must
name a point declared in ``testing/faults.py`` KNOWN_POINTS, and every
declared point must have at least one fire site (a dead registration is
a chaos schedule that can never fire — a test that silently asserts
nothing).  Fire sites must use string literals so the reconciliation
stays static.

metrics — every name exported by perf/collectors.py
(``DEFAULT_METRICS`` ms-scaled histograms, ``COUNT_METRICS`` raw-count
histograms, ``SCALAR_METRICS`` counters/gauges) must exist in the
scheduler metrics ``Registry``, and every metric the Registry
constructs must be exported through exactly those surfaces —
``HistogramVec`` families excepted (their children are dynamic labeled
names).  A metric that is deliberately internal carries
``# graftlint: disable=registry`` on its construction line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, SourceFile, dotted_name, str_constants

CHECK = "registry"

FAULTS_FILE = "testing/faults.py"
METRICS_FILE = "scheduler/metrics.py"
COLLECTORS_FILE = "perf/collectors.py"

_EXPORT_TUPLES = ("DEFAULT_METRICS", "COUNT_METRICS", "SCALAR_METRICS")
_METRIC_CTORS = {"Histogram", "Counter", "Gauge"}
_METRIC_FAMILIES = {"HistogramVec"}  # dynamic children: exempt from export


def _endswith(src: SourceFile, suffix: str) -> bool:
    return src.relpath.replace("\\", "/").endswith(suffix)


def _declared_points(src: SourceFile) -> Tuple[Set[str], int]:
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "KNOWN_POINTS"
            for t in node.targets
        ):
            return set(str_constants(node.value)), node.lineno
    return set(), 1


def _fire_sites(src: SourceFile) -> List[Tuple[str, int]]:
    """(point, line) for every faults.fire()/fire() call with a literal
    first argument; non-literal args come back as ("<dynamic>", line)."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        last = name.split(".")[-1]
        if last != "fire":
            continue
        # `fire(...)` bare or `<alias>.fire(...)` where the alias looks
        # like the faults module; anything else named .fire is skipped
        if "." in name and not name.split(".")[-2].endswith("faults"):
            # e.g. registry.fire inside faults.py itself, or most_recent_fire
            if name.split(".")[-2] not in ("faults",):
                continue
        if not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, node.lineno))
        else:
            out.append(("<dynamic>", node.lineno))
    return out


def _registry_metrics(src: SourceFile) -> Dict[str, Tuple[str, int]]:
    """metric name -> (ctor kind, line) from the Registry class body."""
    out: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Registry":
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id in (_METRIC_CTORS | _METRIC_FAMILIES)
                    and sub.args
                    and isinstance(sub.args[0], ast.Constant)
                    and isinstance(sub.args[0].value, str)
                ):
                    out[sub.args[0].value] = (sub.func.id, sub.lineno)
    return out


def _export_tuples(src: SourceFile) -> Dict[str, List[Tuple[str, int]]]:
    out: Dict[str, List[Tuple[str, int]]] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id in _EXPORT_TUPLES
            for t in node.targets
        ):
            tname = next(
                t.id for t in node.targets if isinstance(t, ast.Name)
            )
            names = out.setdefault(tname, [])
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    names.append((sub.value, sub.lineno))
    return out


def check(files: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    faults_src = metrics_src = collectors_src = None
    for src in files:
        if _endswith(src, FAULTS_FILE):
            faults_src = src
        elif _endswith(src, METRICS_FILE):
            metrics_src = src
        elif _endswith(src, COLLECTORS_FILE):
            collectors_src = src

    # -- fault points ------------------------------------------------------
    if faults_src is not None:
        declared, decl_line = _declared_points(faults_src)
        fired: Dict[str, List[Tuple[SourceFile, int]]] = {}
        for src in files:
            if src is faults_src:
                continue
            for point, line in _fire_sites(src):
                fired.setdefault(point, []).append((src, line))
        for point, sites in sorted(fired.items()):
            for src, line in sites:
                if src.suppressed(line, CHECK):
                    continue
                if point == "<dynamic>":
                    findings.append(
                        Finding(
                            CHECK, src.relpath, line, "faults.fire",
                            "fault point must be a string literal "
                            "(static reconciliation)",
                        )
                    )
                elif point not in declared:
                    findings.append(
                        Finding(
                            CHECK, src.relpath, line, point,
                            f"fired fault point '{point}' is not declared "
                            "in testing/faults.py KNOWN_POINTS",
                        )
                    )
        for point in sorted(declared - set(fired)):
            if not faults_src.suppressed(decl_line, CHECK):
                findings.append(
                    Finding(
                        CHECK, faults_src.relpath, decl_line, point,
                        f"declared fault point '{point}' has no fire site "
                        "(dead registration)",
                    )
                )

    # -- metrics -----------------------------------------------------------
    if metrics_src is not None and collectors_src is not None:
        registry = _registry_metrics(metrics_src)
        exports = _export_tuples(collectors_src)
        exported: Dict[str, Tuple[str, int]] = {}
        for tname, entries in exports.items():
            for name, line in entries:
                exported[name] = (tname, line)
        for name, (tname, line) in sorted(exported.items()):
            if collectors_src.suppressed(line, CHECK):
                continue
            if name not in registry:
                findings.append(
                    Finding(
                        CHECK, collectors_src.relpath, line, name,
                        f"{tname} exports '{name}' which scheduler/"
                        "metrics.py Registry does not define (dead export)",
                    )
                )
        for name, (kind, line) in sorted(registry.items()):
            if kind in _METRIC_FAMILIES:
                continue
            if metrics_src.suppressed(line, CHECK):
                continue
            if name not in exported:
                surface = (
                    "SCALAR_METRICS" if kind in ("Counter", "Gauge")
                    else "DEFAULT_METRICS/COUNT_METRICS"
                )
                findings.append(
                    Finding(
                        CHECK, metrics_src.relpath, line, name,
                        f"Registry {kind} '{name}' is not exported through "
                        f"perf/collectors.py {surface} (unexported metric)",
                    )
                )
    return findings
