"""graftobl — linear-obligation lint (static pass: "obligations").

A *linear obligation* is a resource acquired on one line that must be
discharged exactly once on every outgoing path of the acquiring
function — bind/requeue/park a popped pod, release an acquired
DispatchArbiter slot or APF seat, confirm-or-forget a cache assume,
decrement an ``*_inflight`` counter, disarm an armed fault registry.
The chaos suites enforce these invariants probabilistically (~75
seeds); this pass enforces them structurally, path by path.

Model (docs/static_analysis.md#obligations has the full grammar):

  * Each :class:`Spec` names the acquire shape (method name + receiver
    regex + which value carries the obligation: the call result, the
    receiver, or the first argument) and the discharge surface (method
    names that retire the obligation when the obligated value is their
    receiver or an argument).
  * The engine abstract-interprets each acquiring function's statement
    tree path-sensitively: ``if``/``else`` fork the obligation state,
    loops join it, ``try`` routes the states observed at every
    statement boundary of the body into the handlers, ``finally``
    transforms every outgoing edge (fall-through, ``return``,
    ``raise``, ``break``/``continue``), and a handler-less
    ``try/finally`` adds the escaping-exception edge explicitly.
  * Ownership TRANSFER discharges without a local release: returning
    or yielding the obligated value, storing it into an attribute
    (``ds._slot = slot`` — the DeviceSolve owns the slot now),
    passing it to a declared hand-off callee (``pool.submit``,
    ``wave.append``, ``threading.Thread``), or iterating a popped
    batch into a loop variable the body discharges.
  * CALL SUMMARIES propagate discharge through helpers: a function
    whose body discharges kind K (seeded for the pipeline's containment
    helpers — ``_fail_bind``, ``_salvage_cycle``, ``release_slot`` —
    and computed for everything else) discharges K when the obligated
    value is passed to it.
  * ``exception_safe`` specs must also discharge on ``raise`` edges
    (explicit ``raise`` statements and the handler-less-``try`` escape
    edge); non-exception-safe kinds (pods, assumes) are contained at
    cycle level by ``_salvage_cycle`` — the runtime ledger
    (analysis/ledger.py, GRAFTLINT_OBLIGATIONS=1) owns that cross-
    function half.

Counter obligations (``_stream_inflight += 1`` / ``_dispatch_inflight
= True``) use the same engine with increment/decrement events instead
of call matching; a decrement with no in-function increment is ignored
(the increment lives in another function — the runtime ledger pairs
those).

The fault-registry spec additionally scans ``tests/*.py`` from disk
(the package walk run_all hands us never includes tests — same trick
as coherence's chaos-family scan); ``with faults.armed(...)`` is
self-discharging and never acquires.

Escape hatch: ``# graftlint: disable=obligations -- <why>`` on the
acquiring line (or its ``def`` line).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from . import Finding, SourceFile, dotted_name

CHECK = "obligations"

# cap on distinct path states tracked per statement boundary; beyond it
# the engine collapses to the union of held obligations (conservative)
_MAX_STATES = 64


@dataclass(frozen=True)
class Spec:
    kind: str
    #: method names whose call acquires the obligation
    acquire_methods: Tuple[str, ...]
    #: regex the receiver's dotted name must match ("" receiver text
    #: for plain-name calls)
    acquire_recv: str
    #: which value carries the obligation: "result" (the assign
    #: target), "receiver", "arg0" (first positional argument), or
    #: "global" (process-global resource, e.g. the fault registry)
    bind: str
    #: method names that retire the obligation when the obligated value
    #: is their receiver or among their arguments (for bind="global":
    #: any call of this name on an acquire_recv-matching receiver)
    discharge_methods: Tuple[str, ...]
    #: callee name tails that take ownership when the value is passed
    transfer_calls: Tuple[str, ...] = ()
    #: helper names seeded as must-discharge for this kind
    summary_seeds: Tuple[str, ...] = ()
    #: relpath substrings the spec applies to (() = every module)
    modules: Tuple[str, ...] = ()
    #: must the obligation also be discharged on raise edges?
    exception_safe: bool = False
    #: treat EVERY call made while the obligation is held as a
    #: potential raise edge (fault registries exist to make arbitrary
    #: calls raise — so any statement between arm and disarm is one)
    calls_may_raise: bool = False


@dataclass(frozen=True)
class CounterSpec:
    kind: str
    #: regex matched against the incremented attribute's dotted name
    attr_re: str
    #: callee name tails whose invocation (or whose passing as an
    #: argument, e.g. ``pool.submit(self._commit_stream_subwave, …)``)
    #: hands the decrement off
    handoff: Tuple[str, ...] = ()
    modules: Tuple[str, ...] = ()


SPECS: Tuple[Spec, ...] = (
    # (a) popped pods: a batch leaving the queue's inflight tier must
    # reach a disposition — dispatched onward, or requeued per-pod
    Spec(
        kind="pod",
        acquire_methods=("pop_batch", "pop"),
        acquire_recv=r"queue",
        bind="result",
        discharge_methods=(
            "done", "delete", "requeue_backoff", "add_unschedulable", "add",
        ),
        transfer_calls=("append", "submit", "put", "extend"),
        summary_seeds=("_dispatch_batch", "_fail_bind", "_salvage_cycle"),
        modules=("scheduler/scheduler.py",),
        exception_safe=False,
    ),
    # (b) DispatchArbiter slot: acquire() admission must be released
    # (directly, via release_slot(), or by handing the slot to the
    # DeviceSolve that releases in its decode finally)
    Spec(
        kind="slot",
        acquire_methods=("acquire",),
        acquire_recv=r"slot|arb",
        bind="receiver",
        discharge_methods=("release", "release_slot"),
        summary_seeds=("release_slot",),
        modules=("models/batch_scheduler.py", "scheduler/scheduler.py"),
        exception_safe=True,
    ),
    # (c) APF seat: a granted Seat must be released exactly once
    Spec(
        kind="seat",
        acquire_methods=("acquire",),
        acquire_recv=r"apf|gate|flow",
        bind="result",
        discharge_methods=("release", "_release"),
        modules=("api/",),
        exception_safe=True,
    ),
    # (d) cache assume: confirm (finish_binding/add_pod) or forget
    Spec(
        kind="assume",
        acquire_methods=("assume",),
        acquire_recv=r"cache",
        bind="arg0",
        discharge_methods=(
            "forget", "forget_key", "finish_binding", "add_pod",
        ),
        transfer_calls=("append", "Thread", "submit", "put", "extend"),
        summary_seeds=("_fail_bind", "_salvage_cycle", "_misspeculate_group"),
        modules=("scheduler/",),
        exception_safe=False,
    ),
    # (f) fault registry: testing/faults.arm() must be disarmed on
    # every path out of the arming test (``with faults.armed(...)`` is
    # self-discharging and never matches)
    Spec(
        kind="fault",
        acquire_methods=("arm",),
        acquire_recv=r"faults|^$",
        bind="global",
        discharge_methods=("disarm",),
        modules=("tests/",),
        exception_safe=True,
        calls_may_raise=True,
    ),
)

COUNTER_SPECS: Tuple[CounterSpec, ...] = (
    # (e) streamed sub-wave inflight gauge: += 1 at hand-off, -= 1 in
    # the commit helper's finally (or the hand-off-failure handler)
    CounterSpec(
        kind="stream_inflight",
        attr_re=r"\._stream_inflight$",
        handoff=("_commit_stream_subwave",),
        modules=("scheduler/scheduler.py",),
    ),
    # (e') watch-dispatch busy flag: armed before fanout, cleared in
    # the loop's finally
    CounterSpec(
        kind="dispatch_inflight",
        attr_re=r"\._dispatch_inflight$",
        modules=("api/store.py",),
    ),
)


# -- obligation state --------------------------------------------------------

# one live obligation: (spec_index, obligated value name, acquire line).
# spec_index < len(SPECS) → keyed spec; else counter spec.
_Ob = Tuple[int, str, int]
_State = FrozenSet[_Ob]


def _spec_of(ob: _Ob):
    idx = ob[0]
    if idx < len(SPECS):
        return SPECS[idx]
    return COUNTER_SPECS[idx - len(SPECS)]


def _root_match(var: str, name: Optional[str]) -> bool:
    """Does `name` denote `var` or an enclosing/enclosed value of it?
    ("info" matches "info.pod"; "info.pod" matches "info.pod")."""
    if not name:
        return False
    return (
        var == name
        or var.startswith(name + ".")
        or name.startswith(var + ".")
    )


@dataclass
class _CallSite:
    tail: str                      # method/function name
    recv: Optional[str]            # dotted receiver ("a.b" of a.b.f())
    arg_names: Tuple[str, ...]     # dotted names appearing in the args
    arg_tails: Tuple[str, ...]     # last components of those names
    line: int


def _calls_in(node: ast.AST) -> List[_CallSite]:
    out: List[_CallSite] = []
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        func = sub.func
        if isinstance(func, ast.Attribute):
            tail = func.attr
            recv = dotted_name(func.value)
        elif isinstance(func, ast.Name):
            tail, recv = func.id, None
        else:
            continue
        names: List[str] = []
        for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
            for n in ast.walk(arg):
                if isinstance(n, (ast.Attribute, ast.Name)):
                    d = dotted_name(n)
                    if d:
                        names.append(d)
        out.append(
            _CallSite(
                tail=tail,
                recv=recv,
                arg_names=tuple(names),
                arg_tails=tuple(n.rsplit(".", 1)[-1] for n in names),
                line=getattr(sub, "lineno", getattr(node, "lineno", 0)),
            )
        )
    return out


def _names_in(node: Optional[ast.AST]) -> List[str]:
    if node is None:
        return []
    out = []
    for n in ast.walk(node):
        if isinstance(n, (ast.Attribute, ast.Name)):
            d = dotted_name(n)
            if d:
                out.append(d)
    return out


# -- call summaries ----------------------------------------------------------

def compute_summaries(
    files: Sequence[SourceFile],
) -> Dict[str, FrozenSet[str]]:
    """name -> kinds the function discharges when the obligated value
    is handed to it.  Seeded for the pipeline's containment helpers,
    computed for everything else: a function whose body calls a
    discharge method of kind K (or decrements a K counter) summarizes
    as discharging K.  Deliberately may-discharge rather than
    must-discharge — looseness here can only hide a leak from the
    static half (the runtime ledger still catches it), never invent
    one."""
    summaries: Dict[str, Set[str]] = {}
    for spec in SPECS:
        for seed in spec.summary_seeds:
            summaries.setdefault(seed, set()).add(spec.kind)
    for cspec in COUNTER_SPECS:
        for seed in cspec.handoff:
            summaries.setdefault(seed, set()).add(cspec.kind)
    for src in files:
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            kinds: Set[str] = set()
            for call in _calls_in(node):
                for spec in SPECS:
                    if call.tail in spec.discharge_methods:
                        kinds.add(spec.kind)
            for sub in ast.walk(node):
                if isinstance(sub, ast.AugAssign) and isinstance(
                    sub.op, ast.Sub
                ):
                    tgt = dotted_name(sub.target)
                    for cspec in COUNTER_SPECS:
                        if tgt and re.search(cspec.attr_re, tgt):
                            kinds.add(cspec.kind)
            if kinds:
                summaries.setdefault(node.name, set()).update(kinds)
    return {k: frozenset(v) for k, v in summaries.items()}


# -- the path-sensitive engine ----------------------------------------------

class _Engine:
    """Abstract interpreter for ONE function body: tracks the set of
    live obligations per path, forking at branches and routing
    exception edges through handlers and finally blocks."""

    def __init__(
        self,
        src: SourceFile,
        symbol: str,
        specs: Sequence[Tuple[int, Spec]],
        cspecs: Sequence[Tuple[int, CounterSpec]],
        summaries: Dict[str, FrozenSet[str]],
    ):
        self.src = src
        self.symbol = symbol
        self.specs = specs
        self.cspecs = cspecs
        self.summaries = summaries
        # acquire line -> (ob, set of leak-edge descriptions)
        self.leaks: Dict[_Ob, Set[str]] = {}
        self.discarded: List[Tuple[int, Spec]] = []

    # .. statement effects ..................................................

    def _exprs_of(self, stmt: ast.stmt) -> List[ast.AST]:
        if isinstance(stmt, ast.Expr):
            return [stmt.value]
        if isinstance(stmt, ast.Assign):
            return [stmt.value]
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            return [stmt.value]
        if isinstance(stmt, ast.AugAssign):
            return [stmt.value]
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return [stmt.iter]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return [item.context_expr for item in stmt.items]
        if isinstance(stmt, ast.Assert):
            return [stmt.test]
        if isinstance(stmt, ast.Raise) and stmt.exc is not None:
            return [stmt.exc]
        return []

    def _apply_simple(self, stmt: ast.stmt, state: _State) -> _State:
        """Discharges/transfers, then acquires, for one non-compound
        statement (or the expression part of a compound one)."""
        held = set(state)
        calls: List[_CallSite] = []
        for part in self._exprs_of(stmt):
            calls.extend(_calls_in(part))

        # 1) discharges + transfers against currently-held obligations
        for ob in list(held):
            spec = _spec_of(ob)
            var = ob[1]
            if isinstance(spec, CounterSpec):
                for call in calls:
                    if call.tail in spec.handoff or any(
                        t in spec.handoff for t in call.arg_tails
                    ):
                        held.discard(ob)
                continue
            for call in calls:
                if call.tail in spec.discharge_methods:
                    if spec.bind == "global":
                        if re.search(spec.acquire_recv, call.recv or ""):
                            held.discard(ob)
                    elif _root_match(var, call.recv) or any(
                        _root_match(var, n) for n in call.arg_names
                    ):
                        held.discard(ob)
                elif call.tail in spec.transfer_calls and any(
                    _root_match(var, n) for n in call.arg_names
                ):
                    held.discard(ob)
                elif spec.kind in self.summaries.get(call.tail, ()) and any(
                    _root_match(var, n) for n in call.arg_names
                ):
                    held.discard(ob)
                elif call.tail in spec.summary_seeds:
                    # seeded CONTAINMENT helpers (_salvage_cycle,
                    # _fail_bind, …) sweep everything in flight of
                    # their kind — they reach the obligated objects
                    # through pipeline state, not through arguments
                    held.discard(ob)
            # attribute store transfers ownership: ds._slot = slot
            if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Attribute) for t in stmt.targets
            ):
                if any(_root_match(var, n) for n in _names_in(stmt.value)):
                    held.discard(ob)

        # 2) counter increment/decrement events
        cev = self._counter_event(stmt)
        if cev is not None:
            idx, var, line, is_push = cev
            if is_push:
                held.add((idx, var, line))
            else:
                for ob in sorted(held, key=lambda o: -o[2]):
                    if ob[0] == idx and ob[1] == var:
                        held.discard(ob)
                        break
                # no matching increment in this function: the pair is
                # cross-function — the runtime ledger's job, not ours

        # 3) acquires
        for idx, spec in self.specs:
            for call in calls:
                if call.tail not in spec.acquire_methods:
                    continue
                if not re.search(spec.acquire_recv, call.recv or ""):
                    continue
                var = self._bind_var(spec, stmt, call)
                if var is None:
                    # bind="result" with the result discarded: the
                    # obligation is unreachable the moment it exists
                    self.discarded.append((call.line, spec))
                    continue
                held.add((idx, var, call.line))
        return frozenset(held)

    def _bind_var(
        self, spec: Spec, stmt: ast.stmt, call: _CallSite
    ) -> Optional[str]:
        if spec.bind == "receiver":
            return call.recv
        if spec.bind == "global":
            return f"<{spec.kind}>"
        if spec.bind == "arg0":
            return call.arg_names[0] if call.arg_names else None
        # bind == "result": the assign target
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            return dotted_name(stmt.targets[0])
        if isinstance(stmt, ast.AnnAssign):
            return dotted_name(stmt.target)
        return None

    def _counter_event(
        self, stmt: ast.stmt
    ) -> Optional[Tuple[int, str, int, bool]]:
        if isinstance(stmt, ast.AugAssign):
            tgt = dotted_name(stmt.target)
            if not tgt:
                return None
            for idx, cspec in self.cspecs:
                if re.search(cspec.attr_re, tgt):
                    if isinstance(stmt.op, ast.Add):
                        return (idx, tgt, stmt.lineno, True)
                    if isinstance(stmt.op, ast.Sub):
                        return (idx, tgt, stmt.lineno, False)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt = dotted_name(stmt.targets[0])
            val = stmt.value
            if (
                tgt
                and isinstance(val, ast.Constant)
                and isinstance(val.value, bool)
            ):
                for idx, cspec in self.cspecs:
                    if re.search(cspec.attr_re, tgt):
                        return (idx, tgt, stmt.lineno, bool(val.value))
        return None

    # .. branch refinement ...................................................

    def _drop_vars(self, test: ast.AST, branch: bool) -> Set[str]:
        """Value names whose obligations are VACUOUS inside the given
        branch of `test`: ``if x is None`` / ``if not batch`` mean no
        seat was granted / the popped collection is empty, so an
        obligation bound to that name cannot exist on that path (the
        acquire and the guard talk about the same value)."""
        if isinstance(test, ast.BoolOp):
            out: Set[str] = set()
            if isinstance(test.op, ast.And) and branch:
                for v in test.values:
                    out |= self._drop_vars(v, True)
            elif isinstance(test.op, ast.Or) and not branch:
                for v in test.values:
                    out |= self._drop_vars(v, False)
            return out
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._drop_vars(test.operand, not branch)
        if (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            n = dotted_name(test.left)
            if n:
                if isinstance(test.ops[0], ast.Is) and branch:
                    return {n}
                if isinstance(test.ops[0], ast.IsNot) and not branch:
                    return {n}
            return set()
        n = dotted_name(test)
        if n and not branch:
            return {n}
        return set()

    def _refine(
        self, test: ast.AST, states: Set[_State], branch: bool
    ) -> Set[_State]:
        drops = self._drop_vars(test, branch)
        if not drops:
            return set(states)
        out: Set[_State] = set()
        for s in states:
            out.add(
                frozenset(
                    ob
                    for ob in s
                    if isinstance(_spec_of(ob), CounterSpec)
                    or not any(_root_match(ob[1], d) for d in drops)
                )
            )
        return out

    # .. control flow ........................................................

    def _join(self, states: Iterable[_State]) -> Set[_State]:
        out = set(states)
        if len(out) > _MAX_STATES:
            merged: Set[_Ob] = set()
            for s in out:
                merged.update(s)
            out = {frozenset(merged)}
        return out

    def exec_block(
        self,
        stmts: Sequence[ast.stmt],
        states: Set[_State],
        mid: Optional[Set[_State]] = None,
    ) -> Tuple[Set[_State], List[Tuple[str, Set[_State]]]]:
        """Returns (fall-through states, exits).  Exit kinds: "return",
        "raise", "break", "continue".  `mid` collects the states at
        every statement boundary (the handler-entry approximation)."""
        exits: List[Tuple[str, Set[_State]]] = []
        for stmt in stmts:
            if mid is not None:
                mid.update(states)
            states, ex = self.exec_stmt(stmt, states, mid)
            exits.extend(ex)
            if not states:
                break
        return states, exits

    def exec_stmt(
        self,
        stmt: ast.stmt,
        states: Set[_State],
        mid: Optional[Set[_State]],
    ) -> Tuple[Set[_State], List[Tuple[str, Set[_State]]]]:
        if isinstance(stmt, ast.Return):
            out: Set[_State] = set()
            for s in states:
                kept = frozenset(
                    ob
                    for ob in s
                    if not any(
                        _root_match(ob[1], n) for n in _names_in(stmt.value)
                    )
                )
                out.add(kept)
            return set(), [("return", out)]
        if isinstance(stmt, ast.Raise):
            states = {self._apply_simple(stmt, s) for s in states}
            return set(), [("raise", set(states))]
        if isinstance(stmt, ast.Break):
            return set(), [("break", set(states))]
        if isinstance(stmt, ast.Continue):
            return set(), [("continue", set(states))]

        if isinstance(stmt, ast.If):
            pre = {self._apply_simple(stmt, s) for s in states}
            then_in = self._refine(stmt.test, pre, True)
            else_in = self._refine(stmt.test, pre, False)
            then_out, then_ex = self.exec_block(stmt.body, then_in, mid)
            else_out, else_ex = self.exec_block(stmt.orelse, else_in, mid)
            return self._join(then_out | else_out), then_ex + else_ex

        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            pre = {self._apply_simple(stmt, s) for s in states}
            body_in = set(pre)
            renamed: Dict[_Ob, _Ob] = {}
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                # iterating an obligated collection moves the per-item
                # obligation onto the loop target for the body's scope
                iter_names = _names_in(stmt.iter)
                tgt = dotted_name(stmt.target)
                if tgt:
                    body_in = set()
                    for s in pre:
                        cur = set(s)
                        for ob in list(cur):
                            if not isinstance(_spec_of(ob), CounterSpec) and any(
                                _root_match(ob[1], n) for n in iter_names
                            ):
                                alias = (ob[0], tgt, ob[2])
                                renamed[alias] = ob
                                cur.discard(ob)
                                cur.add(alias)
                        body_in.add(frozenset(cur))
            body_out, body_ex = self.exec_block(stmt.body, set(body_in), mid)
            # one more pass from the joined state approximates the loop
            body_out2, body_ex2 = self.exec_block(
                stmt.body, self._join(body_in | body_out), mid
            )
            loop_ex: List[Tuple[str, Set[_State]]] = []
            after: Set[_State] = set(body_out | body_out2)
            for kind, sts in body_ex + body_ex2:
                if kind in ("break", "continue"):
                    after |= sts
                else:
                    loop_ex.append((kind, sts))
            if renamed:
                restored: Set[_State] = set()
                for s in after:
                    cur = set(s)
                    for alias, orig in renamed.items():
                        if alias in cur:
                            # the body left a loop-item obligation
                            # live: the collection is still charged
                            cur.discard(alias)
                            cur.add(orig)
                    restored.add(frozenset(cur))
                after = restored
                # zero iterations means the obligated collection was
                # empty — the per-item obligation is vacuously met on
                # the skip path
                pre = {
                    frozenset(ob for ob in s if ob not in renamed.values())
                    for s in pre
                }
            out = self._join(pre | after)
            if stmt.orelse:
                out, else_ex = self.exec_block(stmt.orelse, out, mid)
                loop_ex.extend(else_ex)
            return out, loop_ex

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pre = {self._apply_simple(stmt, s) for s in states}
            return self.exec_block(stmt.body, set(pre), mid)

        if isinstance(stmt, ast.Try):
            body_mid: Set[_State] = set(states)
            body_out, body_ex = self.exec_block(
                stmt.body, set(states), body_mid
            )
            handler_in = self._join(body_mid)
            # handlers consume the body's raise edges (over-approx:
            # assume typed handlers catch — biases toward fewer
            # findings); return/break/continue always pass through
            exits: List[Tuple[str, Set[_State]]] = [
                (k, s)
                for k, s in body_ex
                if k != "raise" or not stmt.handlers
            ]
            fall: Set[_State] = set()
            for handler in stmt.handlers:
                h_out, h_ex = self.exec_block(
                    handler.body, set(handler_in), mid
                )
                fall |= h_out
                exits.extend(h_ex)
            if stmt.orelse:
                body_out, else_ex = self.exec_block(stmt.orelse, body_out, mid)
                exits.extend(else_ex)
            fall |= body_out
            if not stmt.handlers:
                # try/finally with no except: the exception escapes —
                # an explicit raise edge carrying the mid-body states
                exits.append(("raise", handler_in))
            if stmt.finalbody:
                fall, fin_ex = self.exec_block(stmt.finalbody, fall, mid)
                exits = [
                    (kind, self.exec_block(stmt.finalbody, sts, mid)[0])
                    for kind, sts in exits
                ] + fin_ex
            return self._join(fall), exits

        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return set(states), []  # nested defs analyzed separately

        exits: List[Tuple[str, Set[_State]]] = []
        has_call = any(isinstance(n, ast.Call) for n in ast.walk(stmt))
        out: Set[_State] = set()
        risky: Set[_State] = set()
        for s in states:
            post = self._apply_simple(stmt, s)
            out.add(post)
            if has_call and any(
                ob in post
                and isinstance(_spec_of(ob), Spec)
                and _spec_of(ob).calls_may_raise
                for ob in s
            ):
                # the call may raise while the obligation is held on
                # BOTH sides of the statement (strictly between the
                # acquire and the discharge — the acquiring and
                # discharging statements themselves are exempt)
                risky.add(s)
        if risky:
            exits.append(("raise", risky))
        return out, exits

    # .. driver ..............................................................

    def run(self, body: Sequence[ast.stmt]) -> None:
        init: Set[_State] = {frozenset()}
        fall, exits = self.exec_block(body, init)
        for s in fall:
            for ob in s:
                self.leaks.setdefault(ob, set()).add("fall-through return")
        for kind, sts in exits:
            for s in sts:
                for ob in s:
                    spec = _spec_of(ob)
                    if kind == "return":
                        self.leaks.setdefault(ob, set()).add("return")
                    elif kind == "raise":
                        exc_safe = (
                            spec.exception_safe
                            if isinstance(spec, Spec)
                            else True
                        )
                        if exc_safe:
                            self.leaks.setdefault(ob, set()).add("exception")
                    # break/continue at function level: unreachable


# -- module walk -------------------------------------------------------------

def _iter_functions(src: SourceFile):
    """Yield (symbol, node) for every function/method, including
    nested ones (symbol is dotted through the enclosing scopes)."""

    def walk(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sym = f"{prefix}{child.name}"
                yield sym, child
                yield from walk(child, f"{sym}.")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")

    yield from walk(src.tree, "")


def _specs_for(src: SourceFile):
    rel = src.relpath.replace(os.sep, "/")
    specs = [
        (i, s)
        for i, s in enumerate(SPECS)
        if not s.modules or any(m in rel for m in s.modules)
    ]
    cspecs = [
        (len(SPECS) + i, c)
        for i, c in enumerate(COUNTER_SPECS)
        if not c.modules or any(m in rel for m in c.modules)
    ]
    return specs, cspecs


def _has_acquire_shape(node: ast.AST, specs, cspecs) -> bool:
    """Cheap pre-filter: does this function mention any acquire method
    name / counter attribute at all?"""
    names = {s.kind for _ in ()}  # noqa: F841 — clarity only
    meths = {m for _, s in specs for m in s.acquire_methods}
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in meths:
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
            if sub.func.id in meths:
                return True
        if isinstance(sub, (ast.AugAssign, ast.Assign)):
            tgt = (
                sub.target
                if isinstance(sub, ast.AugAssign)
                else (sub.targets[0] if len(sub.targets) == 1 else None)
            )
            d = dotted_name(tgt) if tgt is not None else None
            if d and any(re.search(c.attr_re, d) for _, c in cspecs):
                return True
    return False


def _check_source(
    src: SourceFile,
    summaries: Dict[str, FrozenSet[str]],
    findings: List[Finding],
) -> None:
    specs, cspecs = _specs_for(src)
    if not specs and not cspecs:
        return
    for symbol, node in _iter_functions(src):
        if not _has_acquire_shape(node, specs, cspecs):
            continue
        eng = _Engine(src, symbol, specs, cspecs, summaries)
        eng.run(node.body)
        def_line = node.lineno
        for (idx, var, line), edges in sorted(
            eng.leaks.items(), key=lambda kv: (kv[0][2], kv[0][1])
        ):
            spec = _spec_of((idx, var, line))
            if src.suppressed(line, CHECK) or src.suppressed(def_line, CHECK):
                continue
            findings.append(
                Finding(
                    check=CHECK,
                    file=src.relpath,
                    line=line,
                    symbol=symbol,
                    message=(
                        f"{spec.kind} obligation on '{var}' acquired here "
                        f"leaks on {', '.join(sorted(edges))} path(s): "
                        "every outgoing path must discharge it exactly "
                        "once (release/requeue/forget/decrement, a "
                        "summarized helper, or an ownership transfer)"
                    ),
                )
            )
        for line, spec in eng.discarded:
            if src.suppressed(line, CHECK) or src.suppressed(def_line, CHECK):
                continue
            findings.append(
                Finding(
                    check=CHECK,
                    file=src.relpath,
                    line=line,
                    symbol=symbol,
                    message=(
                        f"{spec.kind} obligation acquired here discards "
                        "the obligated result: nothing can ever "
                        "discharge it"
                    ),
                )
            )


def _test_sources(files: Sequence[SourceFile]) -> List[SourceFile]:
    """Load tests/*.py from disk for the fault-registry spec (the
    package walk never includes them — same root-recovery trick as
    coherence's chaos-family scan).  Returns [] for fixture runs whose
    synthetic paths don't resolve."""
    for src in files:
        if not src.path.endswith(src.relpath):
            continue
        root = src.path[: -len(src.relpath)]
        tests = os.path.join(root, "tests")
        if not os.path.isdir(tests):
            return []
        out: List[SourceFile] = []
        for fn in sorted(os.listdir(tests)):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(tests, fn)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    text = f.read()
                out.append(SourceFile(path, os.path.join("tests", fn), text))
            except (SyntaxError, UnicodeDecodeError, OSError):
                continue
        return out
    return []


def check(
    files: Sequence[SourceFile],
    test_files: Optional[Sequence[SourceFile]] = None,
) -> List[Finding]:
    if test_files is None:
        test_files = _test_sources(files)
    everything = list(files) + list(test_files)
    summaries = compute_summaries(everything)
    findings: List[Finding] = []
    for src in everything:
        _check_source(src, summaries, findings)
    return findings
