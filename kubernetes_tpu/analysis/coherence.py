"""coherence (graftcoh): device-resident caches must be wired whole.

The incremental solve is only correct if every device-resident cache
(DeviceClusterMirror's cluster tensors, PartialsCache's [G, N] partial
scores — and the warm-start residents the ROADMAP plans next) provably
tracks the scheduler cache's generations.  Each resident must be
hand-wired into ~7 discipline surfaces, and a missed wire is a silent
stale-read bug.  This pass makes the wiring a checked contract.

A class declares its device-resident state inline, next to the
``GUARDED_FIELDS`` convention (models/mirror.py, models/partials.py)::

    self._dev = None  # resident: fault=mirror.grow chaos=NODE_CHURN_SEEDS

Grammar: ``# resident:`` followed by space-separated ``key=value``
tokens — ``fault=<point>`` (the resident's registered chaos fault
point), ``chaos=<FAMILY_SEEDS>`` (its seed family in tests/
test_chaos.py), optional ``oracle=<name>`` (the oracle-parity twin when
the class has no ``verify()`` — e.g. the mirror's incremental_grow=False
full-resync path).  Free text after `` -- `` is justification.  Keys
may be split across several annotated fields of one class; the class
union counts.

The discipline matrix, verified per resident class:

  * the class implements ``speculation_point`` / ``rollback`` /
    ``invalidate``, and ``verify`` or a declared ``oracle=`` twin;
  * every choke point that bookmarks / rolls back / invalidates ONE
    resident does it for ALL registered residents (the ``_Cycle``
    bookmark sites, ``_misspeculate_group``, ``_reconcile_leadership``,
    the finalize_pending heal wire) — a site that legitimately touches
    one resident alone carries a justified
    ``# graftlint: disable=coherence`` on the call line;
  * the ``fault=`` point is declared in testing/faults.py KNOWN_POINTS
    and the ``chaos=`` family exists in tests/test_chaos.py;
  * no ``@hot_path`` solver reads a resident field directly — residents
    are consumed through ``sync()`` / gather accessors only.

Per-solve prep grids that are NOT resident (yet) declare it::

    # coherence: rebuilt-per-solve -- <why>
    def prep_spread(...):

The pass fails if a declared rebuild silently starts caching across
solves (attribute/global stores inside it, a caching decorator, or its
call result stored on an attribute anywhere in the tree), and requires
the declaration on the known prep builders so the warm-start PRs
convert declarations to residents instead of discovering them.

The runtime half is the epoch auditor (analysis/epochs.py,
GRAFTLINT_COHERENCE=1).  Import-light: stdlib ``ast`` only.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, SourceFile, dotted_name, str_constants

CHECK = "coherence"

FAULTS_FILE = "testing/faults.py"
CHAOS_FILE = os.path.join("tests", "test_chaos.py")

#: classes known to hold device-resident state: the tree must declare
#: them (a silent un-annotation would retire the whole matrix for them)
REQUIRED_RESIDENTS = frozenset({"DeviceClusterMirror", "PartialsCache"})

#: per-solve prep builders the warm-start ROADMAP item will convert to
#: residents: they must carry the rebuilt-per-solve declaration today
REQUIRED_REBUILDS = frozenset({"prep_spread", "prep_terms", "_cell_grid"})

#: the wiring trio every choke point must apply to ALL residents at once
DISCIPLINE_METHODS = ("speculation_point", "rollback", "invalidate")

_RESIDENT_RE = re.compile(
    r"self\.(\w+)\s*(?::[^=]+)?=.*#\s*resident:\s*(.*)$"
)
_REBUILD_RE = re.compile(r"#\s*coherence:\s*rebuilt-per-solve")
_KV_RE = re.compile(r"(\w+)=(\S+)")


class ResidentClass:
    """One discovered resident-holding class."""

    def __init__(self, src: SourceFile, node: ast.ClassDef):
        self.src = src
        self.node = node
        self.name = node.name
        self.fields: Dict[str, int] = {}   # resident field -> decl line
        self.fault: Optional[str] = None
        self.chaos: Optional[str] = None
        self.oracle: Optional[str] = None
        self.methods = {
            stmt.name
            for stmt in node.body
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
        }


def _parse_annotation(rc: ResidentClass, field: str, line: int, text: str):
    rc.fields[field] = line
    text = text.split("--", 1)[0]
    for key, value in _KV_RE.findall(text):
        if key == "fault":
            rc.fault = value
        elif key == "chaos":
            rc.chaos = value
        elif key == "oracle":
            rc.oracle = value


def _discover_residents(files: List[SourceFile]) -> List[ResidentClass]:
    out: List[ResidentClass] = []
    for src in files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            rc = ResidentClass(src, node)
            end = getattr(node, "end_lineno", None) or node.lineno
            for lineno in range(node.lineno, end + 1):
                if lineno - 1 >= len(src.lines):
                    break
                m = _RESIDENT_RE.search(src.lines[lineno - 1])
                if m:
                    _parse_annotation(rc, m.group(1), lineno, m.group(2))
            if rc.fields:
                out.append(rc)
    return out


def _known_points(files: List[SourceFile]) -> Optional[Set[str]]:
    for src in files:
        if src.relpath.replace("\\", "/").endswith(FAULTS_FILE):
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "KNOWN_POINTS"
                    for t in node.targets
                ):
                    return set(str_constants(node.value))
    return None


def _chaos_families(files: List[SourceFile]) -> Optional[Set[str]]:
    """``*_SEEDS`` names assigned in tests/test_chaos.py — read from
    disk next to the scanned tree (the tests live outside the package
    the lint scans).  None when unavailable (fixture runs)."""
    for src in files:
        if not src.path.endswith(src.relpath):
            continue
        root = src.path[: len(src.path) - len(src.relpath)]
        path = os.path.join(root, CHAOS_FILE)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (SyntaxError, OSError):
            return None
        return {
            t.id
            for node in ast.walk(tree)
            if isinstance(node, ast.Assign)
            for t in node.targets
            if isinstance(t, ast.Name) and t.id.endswith("_SEEDS")
        }
    return None


# -- binding resolution ------------------------------------------------------

def _constructor_bindings(
    files: List[SourceFile], classes: Set[str]
) -> Dict[str, str]:
    """attr/name -> resident class, from ``<t> = ClassName(...)`` sites."""
    bindings: Dict[str, str] = {}
    for src in files:
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            # unwrap `X(...) if cond else None` gate idioms
            values = [node.value]
            if isinstance(node.value, ast.IfExp):
                values = [node.value.body, node.value.orelse]
            cls = None
            for value in values:
                if not isinstance(value, ast.Call):
                    continue
                cname = dotted_name(value.func)
                if cname is not None and cname.split(".")[-1] in classes:
                    cls = cname.split(".")[-1]
                    break
            if cls is None:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for tgt in targets:
                if isinstance(tgt, ast.Attribute):
                    bindings[tgt.attr] = cls
                elif isinstance(tgt, ast.Name):
                    bindings[tgt.id] = cls
    return bindings


def _local_bindings(
    fn: ast.AST, global_bindings: Dict[str, str]
) -> Dict[str, str]:
    """Names bound inside one function: ``x = getattr(o, "_mirror", ..)``
    and ``x = self._mirror`` forms, resolved through the constructor
    binding map."""
    out: Dict[str, str] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        value = node.value
        attr: Optional[str] = None
        if (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id == "getattr"
            and len(value.args) >= 2
            and isinstance(value.args[1], ast.Constant)
            and isinstance(value.args[1].value, str)
        ):
            attr = value.args[1].value
        elif isinstance(value, ast.Attribute):
            attr = value.attr
        if attr is not None and attr in global_bindings:
            out[tgt.id] = global_bindings[attr]
    return out


def _resolve_receiver(
    recv: ast.AST,
    global_bindings: Dict[str, str],
    local_bindings: Dict[str, str],
) -> Optional[str]:
    """Resident class a receiver expression denotes, or None."""
    if isinstance(recv, ast.Attribute):
        return global_bindings.get(recv.attr)
    if isinstance(recv, ast.Name):
        if recv.id in local_bindings:
            return local_bindings[recv.id]
        if recv.id in global_bindings:
            return global_bindings[recv.id]
        # convention fallback: a local unpacked from a bookmark tuple
        # named after the binding attr ("mirror" for "_mirror")
        return global_bindings.get("_" + recv.id)
    return None


# -- rules -------------------------------------------------------------------

def _check_discipline_methods(
    rc: ResidentClass, findings: List[Finding]
) -> None:
    line = min(rc.fields.values())
    for m in DISCIPLINE_METHODS:
        if m not in rc.methods and not rc.src.suppressed(line, CHECK):
            findings.append(
                Finding(
                    CHECK, rc.src.relpath, line, rc.name,
                    f"resident class missing discipline method '{m}' "
                    "(speculation/rollback/invalidate wiring)",
                )
            )
    if (
        "verify" not in rc.methods
        and rc.oracle is None
        and not rc.src.suppressed(line, CHECK)
    ):
        findings.append(
            Finding(
                CHECK, rc.src.relpath, line, rc.name,
                "resident class defines neither verify() nor a declared "
                "'oracle=' twin (no parity gate)",
            )
        )


def _check_registrations(
    rc: ResidentClass,
    known_points: Optional[Set[str]],
    chaos_families: Optional[Set[str]],
    findings: List[Finding],
) -> None:
    line = min(rc.fields.values())
    if rc.src.suppressed(line, CHECK):
        return
    if rc.fault is None:
        findings.append(
            Finding(
                CHECK, rc.src.relpath, line, rc.name,
                "resident declares no 'fault=' point (every resident "
                "needs a registered chaos fault point)",
            )
        )
    elif known_points is not None and rc.fault not in known_points:
        findings.append(
            Finding(
                CHECK, rc.src.relpath, line, rc.name,
                f"resident fault point '{rc.fault}' is not declared in "
                "testing/faults.py KNOWN_POINTS",
            )
        )
    if rc.chaos is None:
        findings.append(
            Finding(
                CHECK, rc.src.relpath, line, rc.name,
                "resident declares no 'chaos=' seed family (every "
                "resident needs a chaos-seed family)",
            )
        )
    elif chaos_families is not None and rc.chaos not in chaos_families:
        findings.append(
            Finding(
                CHECK, rc.src.relpath, line, rc.name,
                f"resident chaos family '{rc.chaos}' not found in "
                "tests/test_chaos.py",
            )
        )


def _iter_functions(src: SourceFile):
    """(qualname, fn node, enclosing class name or None); each function
    yielded exactly once (methods are not re-yielded as bare names)."""
    methods: Set[ast.AST] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods.add(stmt)
                    yield f"{node.name}.{stmt.name}", stmt, node.name
    for node in ast.walk(src.tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node not in methods
        ):
            yield node.name, node, None


def _check_choke_points(
    files: List[SourceFile],
    residents: List[ResidentClass],
    bindings: Dict[str, str],
    findings: List[Finding],
) -> None:
    all_classes = {rc.name for rc in residents}
    if len(all_classes) < 2:
        return  # parity is trivially satisfied with one resident
    resident_names = {rc.name for rc in residents}
    for src in files:
        for qual, fn, cls in _iter_functions(src):
            if cls in resident_names:
                continue  # a resident's own methods manage only itself
            locals_ = _local_bindings(fn, bindings)
            calls: Dict[str, Dict[str, int]] = {}  # method -> class -> line
            suppressed = False
            for node in ast.walk(fn):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in DISCIPLINE_METHODS
                ):
                    continue
                target = _resolve_receiver(
                    node.func.value, bindings, locals_
                )
                if target is None:
                    continue
                if src.suppressed(node.lineno, CHECK):
                    suppressed = True
                    continue
                calls.setdefault(node.func.attr, {}).setdefault(
                    target, node.lineno
                )
            for method, touched in sorted(calls.items()):
                missing = sorted(all_classes - set(touched))
                if not missing or suppressed:
                    continue
                line = min(touched.values())
                findings.append(
                    Finding(
                        CHECK, src.relpath, line, qual,
                        f"calls {method}() on "
                        f"{', '.join(sorted(touched))} but not on "
                        f"{', '.join(missing)}: registered residents "
                        f"must {method} together (discipline matrix)",
                    )
                )


def _is_hot_path(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        name = dotted_name(dec)
        if name is not None and name.split(".")[-1] == "hot_path":
            return True
    return False


def _check_hot_path_reads(
    files: List[SourceFile],
    residents: List[ResidentClass],
    bindings: Dict[str, str],
    findings: List[Finding],
) -> None:
    fields_by_class = {rc.name: set(rc.fields) for rc in residents}
    resident_names = set(fields_by_class)
    for src in files:
        for qual, fn, cls in _iter_functions(src):
            if cls in resident_names or not _is_hot_path(fn):
                continue
            locals_ = _local_bindings(fn, bindings)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Attribute):
                    continue
                target = _resolve_receiver(node.value, bindings, locals_)
                if target is None:
                    continue
                if node.attr not in fields_by_class.get(target, ()):
                    continue
                if src.suppressed(node.lineno, CHECK):
                    continue
                findings.append(
                    Finding(
                        CHECK, src.relpath, node.lineno, qual,
                        f"@hot_path function reads resident field "
                        f"'{target}.{node.attr}' directly — residents "
                        "are consumed through sync()/gather accessors",
                    )
                )


def _rebuild_declared(src: SourceFile, fn: ast.AST) -> bool:
    """The rebuilt-per-solve marker sits on the def line or one of the
    two lines above it (covering a decorator line)."""
    for lineno in range(max(fn.lineno - 2, 1), fn.lineno + 1):
        if lineno - 1 < len(src.lines) and _REBUILD_RE.search(
            src.lines[lineno - 1]
        ):
            return True
    return False


def _check_rebuilds(
    files: List[SourceFile], findings: List[Finding]
) -> None:
    declared: Set[str] = set()
    for src in files:
        for qual, fn, cls in _iter_functions(src):
            if not _rebuild_declared(src, fn):
                continue
            declared.add(fn.name)
            # a declared per-solve rebuild must not persist state
            for node in ast.walk(fn):
                what = None
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    what = "a global/nonlocal statement"
                elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    if any(
                        isinstance(t, ast.Attribute) for t in targets
                    ):
                        what = "an attribute store"
                if what and not src.suppressed(node.lineno, CHECK):
                    findings.append(
                        Finding(
                            CHECK, src.relpath, node.lineno, qual,
                            f"declared rebuilt-per-solve function "
                            f"persists state through {what} — convert "
                            "it to a registered resident instead",
                        )
                    )
            for dec in getattr(fn, "decorator_list", []):
                name = dotted_name(dec) or dotted_name(
                    getattr(dec, "func", dec)
                )
                if name and "cache" in name.split(".")[-1]:
                    if not src.suppressed(dec.lineno, CHECK):
                        findings.append(
                            Finding(
                                CHECK, src.relpath, fn.lineno, qual,
                                "declared rebuilt-per-solve function "
                                f"carries caching decorator '{name}' — "
                                "it would cache across solves",
                            )
                        )
    # the seeded prep builders must be declared
    for src in files:
        for qual, fn, cls in _iter_functions(src):
            if (
                fn.name in REQUIRED_REBUILDS
                and fn.name not in declared
                and not src.suppressed(fn.lineno, CHECK)
            ):
                findings.append(
                    Finding(
                        CHECK, src.relpath, fn.lineno, qual,
                        f"per-solve prep rebuild '{fn.name}' must carry "
                        "'# coherence: rebuilt-per-solve' (declared "
                        "non-resident hot rebuild)",
                    )
                )
    # a rebuild's call result stored on an attribute = silent caching
    for src in files:
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            cname = dotted_name(value.func)
            if cname is None or cname.split(".")[-1] not in declared:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            if not any(isinstance(t, ast.Attribute) for t in targets):
                continue
            if src.suppressed(node.lineno, CHECK):
                continue
            findings.append(
                Finding(
                    CHECK, src.relpath, node.lineno,
                    cname.split(".")[-1],
                    "result of a declared per-solve rebuild stored on "
                    "an attribute — silently caching across solves; "
                    "register it as a resident instead",
                )
            )


def check(
    files: List[SourceFile],
    chaos_families: Optional[Set[str]] = None,
) -> List[Finding]:
    findings: List[Finding] = []
    residents = _discover_residents(files)
    known_points = _known_points(files)
    if chaos_families is None:
        chaos_families = _chaos_families(files)

    # seeded registry: the known resident classes must stay declared
    found = {rc.name for rc in residents}
    for src in files:
        for node in ast.walk(src.tree):
            if (
                isinstance(node, ast.ClassDef)
                and node.name in REQUIRED_RESIDENTS
                and node.name not in found
                and not src.suppressed(node.lineno, CHECK)
            ):
                findings.append(
                    Finding(
                        CHECK, src.relpath, node.lineno, node.name,
                        "class holds device-resident state (seeded "
                        "registry) but declares no '# resident:' field "
                        "annotation",
                    )
                )

    for rc in residents:
        _check_discipline_methods(rc, findings)
        _check_registrations(rc, known_points, chaos_families, findings)

    bindings = _constructor_bindings(files, {rc.name for rc in residents})
    _check_choke_points(files, residents, bindings, findings)
    _check_hot_path_reads(files, residents, bindings, findings)
    _check_rebuilds(files, findings)
    return findings
