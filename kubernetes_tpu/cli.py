"""kubectl-style CLI over the REST API.

Reference: the kubectl verb set (staging/src/k8s.io/kubectl
pkg/cmd/cmd.go) reduced to the operational core — get, describe,
create -f, delete, scale, events, top-level cluster state — speaking
the APIServer's wire protocol.

    python -m kubernetes_tpu.cli --server http://127.0.0.1:8080 get pods
    python -m kubernetes_tpu.cli get nodes
    python -m kubernetes_tpu.cli describe pod default/web-1
    python -m kubernetes_tpu.cli create -f deployment.yaml
    python -m kubernetes_tpu.cli scale deployment front --replicas 5
    python -m kubernetes_tpu.cli delete pod web-1
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

from .api import types as api
from .client.rest import RestClient

# kubectl-ish aliases
KINDS = {
    "pod": "Pod", "pods": "Pod", "po": "Pod",
    "node": "Node", "nodes": "Node", "no": "Node",
    "replicaset": "ReplicaSet", "replicasets": "ReplicaSet", "rs": "ReplicaSet",
    "deployment": "Deployment", "deployments": "Deployment", "deploy": "Deployment",
    "job": "Job", "jobs": "Job",
    "event": "Event", "events": "Event", "ev": "Event",
    "lease": "Lease", "leases": "Lease",
    "service": "Service", "services": "Service", "svc": "Service",
    "endpoints": "Endpoints", "ep": "Endpoints",
    "endpointslice": "EndpointSlice", "endpointslices": "EndpointSlice",
    "eps": "EndpointSlice",
    "configmap": "ConfigMap", "configmaps": "ConfigMap", "cm": "ConfigMap",
    "secret": "Secret", "secrets": "Secret",
    "serviceaccount": "ServiceAccount", "serviceaccounts": "ServiceAccount",
    "sa": "ServiceAccount",
    "resourcequota": "ResourceQuota", "resourcequotas": "ResourceQuota",
    "quota": "ResourceQuota",
    "hpa": "HorizontalPodAutoscaler",
    "horizontalpodautoscaler": "HorizontalPodAutoscaler",
    "horizontalpodautoscalers": "HorizontalPodAutoscaler",
    "pv": "PersistentVolume", "persistentvolumes": "PersistentVolume",
    "pvc": "PersistentVolumeClaim",
    "persistentvolumeclaims": "PersistentVolumeClaim",
    "crd": "CustomResourceDefinition",
    "crds": "CustomResourceDefinition",
    "role": "Role", "roles": "Role",
    "clusterrole": "ClusterRole", "clusterroles": "ClusterRole",
    "rolebinding": "RoleBinding", "rolebindings": "RoleBinding",
    "clusterrolebinding": "ClusterRoleBinding",
    "clusterrolebindings": "ClusterRoleBinding",
}


def _kind(word: str) -> str:
    k = KINDS.get(word.lower())
    if not k:
        raise SystemExit(f"unknown resource kind {word!r} (known: {sorted(set(KINDS.values()))})")
    return k


def _fmt_pod(p: api.Pod) -> List[str]:
    return [
        f"{p.meta.namespace}/{p.meta.name}",
        p.status.phase,
        p.spec.node_name or "<none>",
        f"cpu={p.resource_requests().get(api.CPU, 0)}m",
    ]


def _fmt_any(o) -> List[str]:
    name = f"{o.meta.namespace}/{o.meta.name}" if o.meta.namespace else o.meta.name
    if isinstance(o, api.Pod):
        return _fmt_pod(o)
    if isinstance(o, api.Node):
        alloc = o.status.allocatable
        return [name, f"cpu={alloc.get(api.CPU, 0)}m", f"pods={alloc.get(api.PODS, 0)}"]
    if isinstance(o, api.Deployment):
        return [name, f"{o.status.ready_replicas}/{o.spec.replicas} ready"]
    if isinstance(o, api.ReplicaSet):
        return [name, f"{o.status.ready_replicas}/{o.spec.replicas} ready"]
    if isinstance(o, api.Job):
        return [name, f"succeeded={o.status.succeeded}", f"active={o.status.active}"]
    if isinstance(o, api.Event):
        return [name, o.type, o.reason, f"x{o.count}", o.message[:60]]
    if isinstance(o, api.Service):
        ports = ",".join(f"{p.port}/{p.protocol}" for p in o.spec.ports)
        return [name, o.spec.type, o.spec.cluster_ip or "<none>", ports]
    if isinstance(o, api.Endpoints):
        addrs = [a.ip for s in o.subsets for a in s.addresses]
        shown = ",".join(addrs[:3]) + ("..." if len(addrs) > 3 else "")
        return [name, shown or "<none>"]
    if isinstance(o, api.EndpointSlice):
        ready = sum(1 for e in o.endpoints if e.conditions.ready)
        return [name, o.address_type, f"{ready}/{len(o.endpoints)} ready"]
    return [name]


def _ns_for(kind: str, args) -> str:
    # cluster-scoped kinds live in namespace ""
    return "" if kind in api.CLUSTER_SCOPED_KINDS else args.namespace


def cmd_get(client: RestClient, args) -> None:
    kind = _kind(args.resource)
    if args.name:
        obj = client.get(kind, args.name, _ns_for(kind, args))
        print("  ".join(_fmt_any(obj)))
        return
    namespace = (
        None
        if kind in api.CLUSTER_SCOPED_KINDS
        or getattr(args, "all_namespaces", False)
        else args.namespace
    )
    items, rv = client.list(
        kind,
        namespace=namespace,
        label_selector=getattr(args, "selector", None),
        field_selector=getattr(args, "field_selector", None),
    )
    for o in items:
        print("  ".join(_fmt_any(o)))
    print(f"# {len(items)} {kind}(s) at rv {rv}", file=sys.stderr)


def cmd_describe(client: RestClient, args) -> None:
    from .api import wire

    kind = _kind(args.resource)
    obj = client.get(kind, args.name, _ns_for(kind, args))
    print(json.dumps(wire.to_wire(obj), indent=2, default=str))


def cmd_create(client: RestClient, args) -> None:
    import yaml

    from .api import kubeyaml

    with open(args.filename) as f:
        docs = list(yaml.safe_load_all(f))
    for d in docs:
        if not d:
            continue
        kind = d.get("kind", "Pod")
        conv = kubeyaml.CONVERTERS.get(kind)
        if conv is None:
            raise SystemExit(
                f"create -f supports {sorted(kubeyaml.CONVERTERS)}; got {kind}"
            )
        created = client.create(conv(d))
        print(f"{kind.lower()}/{created.meta.name} created")


def _manifest_patch(obj):
    """Merge patch carrying only the fields the manifest SET: the
    object's wire doc diffed against a default-constructed one, so
    server-owned fields (node_name, finalizers, timestamps, status)
    never ride along and stomp live state.  kubectl's three-way apply
    gets the same effect via the last-applied annotation; diff-vs-default
    is the stateless equivalent for our wire model (a field explicitly
    set to its default is treated as unset — documented divergence)."""
    from .api import wire

    def diff(doc, base):
        if isinstance(doc, dict) and isinstance(base, dict):
            out = {}
            for k, v in doc.items():
                if k == "__t":
                    continue
                if k not in base:
                    out[k] = v
                else:
                    sub = diff(v, base[k])
                    if sub is not None:
                        out[k] = sub
            return out or None
        return doc if doc != base else None

    doc = wire.to_wire(obj)
    base = wire.to_wire(type(obj)())
    patch = diff(doc, base) or {}
    patch.pop("status", None)
    meta = patch.get("meta")
    if meta:
        for managed in (
            "resource_version", "uid", "deletion_timestamp", "finalizers",
            "creation_timestamp",
        ):
            meta.pop(managed, None)
    return patch


def cmd_apply(client: RestClient, args) -> None:
    """create-or-patch from a manifest (kubectl apply's effective
    behavior for our wire model: absent objects are created; existing
    objects receive the manifest's fields as an RFC 7386 merge patch —
    the reference's three-way server-side apply reduces to this when no
    other field manager contests ownership)."""
    import yaml

    from .api import kubeyaml, wire

    with open(args.filename) as f:
        docs = list(yaml.safe_load_all(f))
    for d in docs:
        if not d:
            continue
        kind = d.get("kind", "Pod")
        conv = kubeyaml.CONVERTERS.get(kind)
        if conv is None:
            raise SystemExit(
                f"apply -f supports {sorted(kubeyaml.CONVERTERS)}; got {kind}"
            )
        obj = conv(d)
        ns = "" if kind in api.CLUSTER_SCOPED_KINDS else obj.meta.namespace
        try:
            client.get(kind, obj.meta.name, ns)
        except Exception:
            client.create(obj)
            print(f"{kind.lower()}/{obj.meta.name} created")
            continue
        patch = _manifest_patch(obj)
        if patch:
            client.patch(kind, obj.meta.name, patch, namespace=ns)
        print(f"{kind.lower()}/{obj.meta.name} configured")


def cmd_edit(client: RestClient, args) -> None:
    """fetch -> $EDITOR -> update (kubectl edit): the object's wire JSON
    round-trips through the editor; an unchanged buffer is a no-op."""
    import os
    import subprocess
    import tempfile

    from .api import wire

    kind = _kind(args.resource)
    obj = client.get(kind, args.name, _ns_for(kind, args))
    doc = json.dumps(wire.to_wire(obj), indent=2, default=str)
    import shlex

    editor = shlex.split(os.environ.get("EDITOR", "vi"))
    with tempfile.NamedTemporaryFile(
        "w+", suffix=".json", delete=False
    ) as f:
        f.write(doc)
        path = f.name
    try:
        subprocess.run(editor + [path], check=True)
        with open(path) as f:
            edited = f.read()
        if edited == doc:
            print("Edit cancelled, no changes made.")
            return
        client.update(wire.from_wire(json.loads(edited)))
        print(f"{args.resource.lower()}/{args.name} edited")
    finally:
        os.unlink(path)


def cmd_logs(client: RestClient, args) -> None:
    """Lifecycle log for a pod (kubectl logs): the hollow runtime has
    no container stdout, so the log surface is the pod's recorded
    lifecycle — its Events plus agent-reported restart counts — which
    is what the reference's events+logs pair carries for a pod that
    never wrote output."""
    pod = client.get("Pod", args.name, args.namespace)
    events, _ = client.list("Event", namespace=args.namespace)
    mine = sorted(
        (e for e in events if e.involved_object.name == args.name),
        key=lambda e: e.last_timestamp,
    )
    for e in mine:
        print(f"{e.type}\t{e.reason}\tx{e.count}\t{e.message}")
    rc = pod.status.restart_counts
    if rc:
        print(f"-- restarts: {dict(rc)}")
    print(
        f"-- phase: {pod.status.phase}"
        + (f" on {pod.spec.node_name}" if pod.spec.node_name else "")
        + (f" ip {pod.status.pod_ip}" if pod.status.pod_ip else "")
    )


def cmd_delete(client: RestClient, args) -> None:
    kind = _kind(args.resource)
    client.delete(kind, args.name, _ns_for(kind, args))
    print(f"{args.resource.lower()}/{args.name} deleted")


def cmd_scale(client: RestClient, args) -> None:
    kind = _kind(args.resource)
    if kind not in ("Deployment", "ReplicaSet", "Job"):
        raise SystemExit(f"cannot scale {kind}")
    obj = client.get(kind, args.name, args.namespace)
    if kind == "Job":
        obj.spec.parallelism = args.replicas
    else:
        obj.spec.replicas = args.replicas
    client.update(obj)
    print(f"{args.resource.lower()}/{args.name} scaled to {args.replicas}")


def cmd_patch(client: RestClient, args) -> None:
    kind = _kind(args.resource)
    client.patch(
        kind, args.name, json.loads(args.patch),
        namespace=_ns_for(kind, args), subresource=args.subresource,
    )
    print(f"{args.resource.lower()}/{args.name} patched")


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="kubernetes_tpu.cli", description=__doc__)
    ap.add_argument("--server", default="http://127.0.0.1:8080")
    ap.add_argument("--token", default=None, help="bearer token")
    ap.add_argument("-n", "--namespace", default="default")
    sub = ap.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("get")
    g.add_argument("resource")
    g.add_argument("name", nargs="?")
    g.add_argument("-A", "--all-namespaces", action="store_true")
    g.add_argument("-l", "--selector", default=None,
                   help="label selector, e.g. app=web,tier!=cache")
    g.add_argument("--field-selector", default=None,
                   help="field selector, e.g. spec.nodeName=n0")
    g.set_defaults(fn=cmd_get)

    d = sub.add_parser("describe")
    d.add_argument("resource")
    d.add_argument("name")
    d.set_defaults(fn=cmd_describe)

    c = sub.add_parser("create")
    c.add_argument("-f", "--filename", required=True)
    c.set_defaults(fn=cmd_create)

    ap_ = sub.add_parser("apply")
    ap_.add_argument("-f", "--filename", required=True)
    ap_.set_defaults(fn=cmd_apply)

    ed = sub.add_parser("edit")
    ed.add_argument("resource")
    ed.add_argument("name")
    ed.set_defaults(fn=cmd_edit)

    lg = sub.add_parser("logs")
    lg.add_argument("name")
    lg.set_defaults(fn=cmd_logs)

    rm = sub.add_parser("delete")
    rm.add_argument("resource")
    rm.add_argument("name")
    rm.set_defaults(fn=cmd_delete)

    s = sub.add_parser("scale")
    s.add_argument("resource")
    s.add_argument("name")
    s.add_argument("--replicas", type=int, required=True)
    s.set_defaults(fn=cmd_scale)

    p = sub.add_parser("patch")
    p.add_argument("resource")
    p.add_argument("name")
    p.add_argument("-p", "--patch", required=True,
                   help="RFC 7386 merge patch as JSON")
    p.add_argument("--subresource", default=None, choices=[None, "status"])
    p.set_defaults(fn=cmd_patch)

    args = ap.parse_args(argv)
    client = RestClient(args.server, token=args.token)
    args.fn(client, args)


if __name__ == "__main__":
    main()
