"""Node-axis-sharded solves: the multi-chip scheduling step.

The reference scales its hot loop with 16 goroutines and adaptive node
sampling (parallelize/parallelism.go, schedule_one.go:662); the TPU-native
scale-out shards the *node axis* of every cluster tensor across a device
mesh with shard_map.  Each chip filters and scores its node shard, reduces
its local champion, and a pmax/pmin pair elects the global winner — the
ring-reduction analogue sketched in SURVEY.md section 5.7.  The winning
shard applies the assume-update locally; per-pod state (requested, ports)
never leaves its shard, so per-step communication is O(1) scalars on ICI
(plus the wavefront's O(K) merged candidate list per wave), independent
of cluster size.

All three solver families follow the ops.auction pattern — ONE
implementation, two layouts: ops.assign.greedy_assign /
wavefront_assign and ops.auction.auction_assign take an ``axis_name``
and internally switch their node-axis boundary crossings to
ownership-masked psums, pmax/pmin elections, and all_gather merges.
The wrappers here only set up the shard_map specs, so the sharded
solvers cannot drift from the single-chip ones.

Tie-break parity with the single-chip path: lowest node index among
max-score nodes (argmax-first-index locally, pmin on the winner index
globally).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """jax.shard_map graduated from jax.experimental after 0.4.x and
    renamed check_rep to check_vma; accept both APIs so the sharded
    solvers run on either jax generation."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )

from ..analysis import retrace
from ..ops.assign import (
    DEFAULT_WAVE_CAP,
    FeatureFlags,
    SolveResult,
    features_of,
    greedy_assign,
    needs_topo,
    plan_waves,
    required_topo_z,
    required_topo_z_split,
    wavefront_assign,
)
from ..ops.auction import (
    AuctionResult,
    auction_assign,
    auction_features_ok,
    default_tie_k,
)
from ..ops.partials import ClassStatics
from ..ops.schema import (
    ClusterTensors,
    PrefPodTable,
    Snapshot,
    SpreadTable,
    TermTable,
    num_groups,
)
from ..ops.scores import DEFAULT_SCORE_CONFIG, ScoreConfig

AXIS = "nodes"

# PartitionSpec for each ClusterTensors field: node axis sharded, the rest
# replicated.  taint_bits is effect-major so its node axis is dim 1.
CLUSTER_SPECS = ClusterTensors(
    allocatable=P(AXIS, None),
    requested=P(AXIS, None),
    nonzero_requested=P(AXIS, None),
    node_valid=P(AXIS),
    name_id=P(AXIS),
    label_bits=P(AXIS, None),
    taint_bits=P(None, AXIS, None),
    port_bits=P(AXIS, None),
    topo_ids=P(AXIS, None),
    image_bits=P(AXIS, None),
    slice_id=P(AXIS),
    torus_coords=P(AXIS, None),
    slice_dims=P(AXIS, None),
    slice_pos=P(AXIS),
)


# Warm-start statics ([C, N] per-class triples gathered from the
# device-resident PartialsCache): node axis sharded like every other
# [·, N] table — the resident store carries exactly this layout, so a
# warm mesh solve consumes it without resharding.
STATICS_SPECS = ClassStatics(
    sfeas=P(None, AXIS), aff=P(None, AXIS), taint=P(None, AXIS)
)


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(devices, (AXIS,))


def mesh_signature(mesh: Mesh) -> tuple:
    """Hashable mesh-shape component of a sharded executable key (the
    retrace tracker's and the prewarm pool's mesh discriminator)."""
    return ("mesh",) + tuple(int(d) for d in mesh.devices.shape)


def _spread_specs(rep):
    return SpreadTable(
        valid=rep, slot=rep, max_skew=rep, min_domains=rep, hard=rep,
        owner_sel_idx=rep, owner_keys=rep, node_matches=P(None, AXIS),
        pod_matches=rep, pod_idx=rep,
    )


def _term_specs(rep):
    return TermTable(
        valid=rep, slot=rep, node_matches=P(None, AXIS),
        node_owners=P(None, AXIS), matches_incoming=rep, aff_idx=rep,
        anti_idx=rep, self_match_all=rep,
    )


def _prefpod_specs(rep):
    return PrefPodTable(
        valid=rep, slot=rep, node_counts=P(None, AXIS),
        owner_weight=P(None, AXIS), matches_incoming=rep, pod_idx=rep,
        pod_weight=rep,
    )


def _snapshot_in_specs(parts):
    """shard_map in_specs for the 8 Snapshot components: cluster tensors
    node-sharded, pod/constraint tables replicated except their [·, N]
    per-node count matrices."""
    rep = P()
    (cluster, pods, sel, pref, spread, terms, prefpod, images) = parts
    return (
        CLUSTER_SPECS,
        jax.tree.map(lambda _: rep, pods),
        jax.tree.map(lambda _: rep, sel),
        jax.tree.map(lambda _: rep, pref),
        _spread_specs(rep),
        _term_specs(rep),
        _prefpod_specs(rep),
        jax.tree.map(lambda _: rep, images),
    )


def _check_divisible(n: int, mesh: Mesh) -> None:
    n_dev = mesh.devices.size
    if n % n_dev:
        raise ValueError(
            f"padded node count {n} not divisible by mesh size {n_dev}"
        )


def sharded_greedy_assign(
    snapshot: Snapshot,
    mesh: Mesh,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    topo_z: Optional[int] = None,
    features: Optional[FeatureFlags] = None,
    n_groups: int = 0,
    statics: Optional[ClassStatics] = None,
) -> SolveResult:
    """greedy_assign with the node axis sharded over `mesh`.

    Placement semantics are identical to ops.assign.greedy_assign; only
    the data layout differs — this wrapper sets up shard_map specs and
    calls greedy_assign(axis_name=...), which handles the elections and
    constraint-state broadcasts internally.  Requires the padded node
    count to be divisible by the mesh size (SnapshotBuilder pads to
    powers of two, mesh sizes are powers of two, so this holds whenever
    the cluster bucket is at least one row per chip;
    TPUBatchScheduler._dispatch falls back to the single chip — counted
    in `sharded_solve_fallbacks` — otherwise).

    Constraint count state ([C/T, Z]) is small and kept replicated: each
    shard scatter-builds counts from its node shard, a psum replicates
    them, and per-placement updates are broadcast from the winning
    shard.  Gang all-or-nothing (n_groups) runs the shared post-pass
    with per-shard ownership masking."""
    if features is None:
        features = features_of(snapshot)
    if topo_z is None:
        topo_z = required_topo_z(snapshot)
    parts = jax.tree.map(jnp.asarray, tuple(snapshot))
    _check_divisible(parts[0].allocatable.shape[0], mesh)

    rep = P()
    slice_specs = (
        {
            "frag_score": rep, "carveouts": rep,
            "contiguous_gangs": rep, "carveout_fallbacks": rep,
        }
        if features.slices
        else {}
    )
    out_specs = SolveResult(
        assignment=rep, scores=rep, feasible_counts=rep,
        cluster=CLUSTER_SPECS, reasons=rep, **slice_specs,
    )

    if statics is None:

        @partial(
            _shard_map,
            mesh=mesh,
            in_specs=_snapshot_in_specs(parts),
            out_specs=out_specs,
            check_vma=False,
        )
        def run(cl, pods, sel, pref, spread, terms, prefpod, images):
            local = Snapshot(
                cl, pods, sel, pref, spread, terms, prefpod, images
            )
            return greedy_assign(
                local, cfg, topo_z=topo_z, features=features,
                n_groups=n_groups, axis_name=AXIS,
            )

        return run(*parts)

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_snapshot_in_specs(parts) + (STATICS_SPECS,),
        out_specs=out_specs,
        check_vma=False,
    )
    def run_warm(cl, pods, sel, pref, spread, terms, prefpod, images, st):
        local = Snapshot(cl, pods, sel, pref, spread, terms, prefpod, images)
        return greedy_assign(
            local, cfg, topo_z=topo_z, features=features,
            n_groups=n_groups, axis_name=AXIS, statics=st,
        )

    return run_warm(*parts, jax.tree.map(jnp.asarray, statics))


def sharded_wavefront_assign(
    snapshot: Snapshot,
    wave_members,
    mesh: Mesh,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    topo_z: Optional[int] = None,
    features: Optional[FeatureFlags] = None,
    n_groups: int = 0,
    statics: Optional[ClassStatics] = None,
) -> SolveResult:
    """wavefront_assign with the node axis sharded over `mesh` — the
    production mesh route for large greedy batches: ~P/W wave steps
    instead of P, each wave evaluated on all chips in parallel.

    The wave plan stays a replicated host-side device argument
    (plan_waves — pod-space only), the batched [K, N] evaluation runs
    per shard, the top-(K+1) candidate lists merge through one
    all_gather per wave, and the O(K) mini-scan corrections are computed
    on psum-replicated picked rows so every shard reaches the same
    choice without per-pod elections (see wavefront_assign's axis_name
    docstring).  Placements — and the serialized-wave / fit-flip
    fallback counters — are bit-identical to the single-chip wavefront,
    which is itself scan-identical."""
    if features is None:
        features = features_of(snapshot)
    if topo_z is None:
        topo_z = required_topo_z(snapshot)
    parts = jax.tree.map(jnp.asarray, tuple(snapshot))
    _check_divisible(parts[0].allocatable.shape[0], mesh)
    members = jnp.asarray(wave_members, jnp.int32)

    rep = P()
    out_specs = SolveResult(
        assignment=rep, scores=rep, feasible_counts=rep,
        cluster=CLUSTER_SPECS, reasons=rep, wave_count=rep,
        wave_fallbacks=rep,
    )

    if statics is None:

        @partial(
            _shard_map,
            mesh=mesh,
            in_specs=_snapshot_in_specs(parts) + (rep,),
            out_specs=out_specs,
            check_vma=False,
        )
        def run(cl, pods, sel, pref, spread, terms, prefpod, images, mem):
            local = Snapshot(
                cl, pods, sel, pref, spread, terms, prefpod, images
            )
            return wavefront_assign(
                local, mem, cfg, topo_z=topo_z, features=features,
                n_groups=n_groups, axis_name=AXIS,
            )

        return run(*parts, members)

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_snapshot_in_specs(parts) + (rep, STATICS_SPECS),
        out_specs=out_specs,
        check_vma=False,
    )
    def run_warm(cl, pods, sel, pref, spread, terms, prefpod, images, mem, st):
        local = Snapshot(cl, pods, sel, pref, spread, terms, prefpod, images)
        return wavefront_assign(
            local, mem, cfg, topo_z=topo_z, features=features,
            n_groups=n_groups, axis_name=AXIS, statics=st,
        )

    return run_warm(*parts, members, jax.tree.map(jnp.asarray, statics))


def sharded_auction_assign(
    snapshot: Snapshot,
    mesh: Mesh,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    n_groups: int = 0,
    tie_seed: int = 0,
    max_rounds: int = 64,
    features: Optional[FeatureFlags] = None,
    topo_z=None,
    tie_k: Optional[int] = None,
) -> AuctionResult:
    """auction_assign with the node axis sharded over `mesh` — the
    multi-chip joint solve (the north-star gang-burst config at scales
    one chip's HBM can't hold).

    One implementation, two layouts: this wrapper only sets up
    shard_map specs and calls ops.auction.auction_assign(axis_name=...)
    — pod-space state is replicated, node-space state sharded, and the
    boundary crossings are ownership-masked psums, a pmax/pmin election,
    and an all_gather tie-set merge (see auction_assign's docstring).
    Placements are bit-identical to the single-chip auction.
    """
    if features is None:
        features = features_of(snapshot)
    if not auction_features_ok(features):
        raise ValueError(
            "auction does not cover in-batch host ports or "
            "affinity-direction inter-pod terms; route through "
            "sharded_greedy_assign"
        )
    if topo_z is None:
        topo_z = required_topo_z_split(snapshot)
    if tie_k is None:
        tie_k = default_tie_k(snapshot)
    parts = jax.tree.map(jnp.asarray, tuple(snapshot))
    n = parts[0].allocatable.shape[0]
    _check_divisible(n, mesh)
    # tie_k bounds the GLOBAL tie list; each shard's local top_k clamps
    # to its shard size inside auction_assign and the all_gather merge
    # restores the global length
    tie_k = min(tie_k, n)

    rep = P()
    out_specs = AuctionResult(
        assignment=rep, scores=rep, rounds=rep, gang_dropped=rep,
        cluster=CLUSTER_SPECS, reasons=rep,
        debug_sp_counts=P(None, AXIS) if features.spread else None,
    )

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_snapshot_in_specs(parts),
        out_specs=out_specs,
        check_vma=False,
    )
    def run(cl, pods, sel, pref, spread, terms, prefpod, images):
        local = Snapshot(cl, pods, sel, pref, spread, terms, prefpod, images)
        return auction_assign(
            local, cfg, n_groups=n_groups, tie_seed=tie_seed,
            max_rounds=max_rounds, features=features, topo_z=topo_z,
            tie_k=tie_k, axis_name=AXIS,
        )

    return run(*parts)


# -- jitted wrappers ---------------------------------------------------------
#
# Mirrors of ops.assign's *_jit closures for the mesh layout: one
# executable per (shape bucket, statics, MESH SHAPE).  Every dispatch
# reports to the recompile-discipline tracker (analysis/retrace.py) with
# the mesh shape folded into the signature — a mesh-mode batch must
# never silently compile a fresh executable in steady state.  `.jitted`
# exposes the raw jit for the prewarm pool's AOT lower().compile().


def sharded_greedy_jit(mesh: Mesh, cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    mesh_sig = mesh_signature(mesh)

    @partial(jax.jit, static_argnums=(1, 2, 3))
    def run(
        snapshot: Snapshot, topo_z: int, features: FeatureFlags,
        n_groups: int,
    ) -> SolveResult:
        return sharded_greedy_assign(
            snapshot, mesh, cfg, topo_z=topo_z, features=features,
            n_groups=n_groups,
        )

    @partial(jax.jit, static_argnums=(2, 3, 4))
    def run_warm(
        snapshot: Snapshot, statics, topo_z: int, features: FeatureFlags,
        n_groups: int,
    ) -> SolveResult:
        return sharded_greedy_assign(
            snapshot, mesh, cfg, topo_z=topo_z, features=features,
            n_groups=n_groups, statics=statics,
        )

    def call(
        snapshot: Snapshot,
        topo_z: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
        n_groups: Optional[int] = None,
        statics=None,
    ) -> SolveResult:
        if features is None:
            features = features_of(snapshot)
        if topo_z is None:
            topo_z = (
                required_topo_z(snapshot) if needs_topo(features) else 1
            )
        if n_groups is None:
            n_groups = num_groups(snapshot)
        if n_groups > 0:
            from ..utils.vocab import pad_dim

            n_groups = pad_dim(n_groups, 1)
        if statics is not None:
            out = run_warm(snapshot, statics, topo_z, features, n_groups)
            retrace.note(
                "greedy-sharded-warm", run_warm,
                lambda: retrace.signature(
                    (snapshot, statics),
                    (topo_z, features, n_groups, mesh_sig),
                ),
            )
            return out
        out = run(snapshot, topo_z, features, n_groups)
        retrace.note(
            "greedy-sharded", run,
            lambda: retrace.signature(
                snapshot, (topo_z, features, n_groups, mesh_sig)
            ),
        )
        return out

    call.jitted = run  # raw jit, for AOT prewarm (lower().compile())
    call.jitted_warm = run_warm
    return call


def sharded_wavefront_jit(mesh: Mesh, cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    """Jitted sharded wavefront: one executable per (shape bucket,
    topo_z, features, n_groups, wave shape, mesh shape).  The wave plan
    stays a device argument so repartitions reuse the executable."""
    mesh_sig = mesh_signature(mesh)

    @partial(jax.jit, static_argnums=(2, 3, 4))
    def run(
        snapshot: Snapshot, wave_members, topo_z: int,
        features: FeatureFlags, n_groups: int,
    ) -> SolveResult:
        return sharded_wavefront_assign(
            snapshot, wave_members, mesh, cfg, topo_z=topo_z,
            features=features, n_groups=n_groups,
        )

    @partial(jax.jit, static_argnums=(3, 4, 5))
    def run_warm(
        snapshot: Snapshot, wave_members, statics, topo_z: int,
        features: FeatureFlags, n_groups: int,
    ) -> SolveResult:
        return sharded_wavefront_assign(
            snapshot, wave_members, mesh, cfg, topo_z=topo_z,
            features=features, n_groups=n_groups, statics=statics,
        )

    def call(
        snapshot: Snapshot,
        wave_members=None,
        topo_z: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
        n_groups: Optional[int] = None,
        wave_cap: int = DEFAULT_WAVE_CAP,
        statics=None,
    ) -> SolveResult:
        if features is None:
            features = features_of(snapshot)
        if topo_z is None:
            topo_z = (
                required_topo_z(snapshot) if needs_topo(features) else 1
            )
        if n_groups is None:
            n_groups = num_groups(snapshot)
        if n_groups > 0:
            from ..utils.vocab import pad_dim

            n_groups = pad_dim(n_groups, 1)
        if wave_members is None:
            wave_members = plan_waves(
                snapshot, features=features, wave_cap=wave_cap
            ).members
        members = jnp.asarray(wave_members, jnp.int32)
        if statics is not None:
            out = run_warm(snapshot, members, statics, topo_z, features,
                           n_groups)
            retrace.note(
                "wavefront-sharded-warm", run_warm,
                lambda: retrace.signature(
                    (snapshot, members, statics),
                    (topo_z, features, n_groups, mesh_sig),
                ),
            )
            return out
        out = run(snapshot, members, topo_z, features, n_groups)
        retrace.note(
            "wavefront-sharded", run,
            lambda: retrace.signature(
                (snapshot, members), (topo_z, features, n_groups, mesh_sig)
            ),
        )
        return out

    call.jitted = run  # raw jit, for AOT prewarm (lower().compile())
    call.jitted_warm = run_warm
    return call


def sharded_auction_jit(mesh: Mesh, cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    mesh_sig = mesh_signature(mesh)

    @partial(jax.jit, static_argnums=(1, 2, 3, 4))
    def run(snapshot, n_groups, features, topo_z, tie_k):
        return sharded_auction_assign(
            snapshot, mesh, cfg, n_groups=n_groups, features=features,
            topo_z=topo_z, tie_k=tie_k,
        )

    def call(
        snapshot: Snapshot,
        n_groups: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
        topo_z=None,
        tie_k: Optional[int] = None,
    ) -> AuctionResult:
        if features is None:
            features = features_of(snapshot)
        if n_groups is None:
            n_groups = num_groups(snapshot)
        if topo_z is None:
            topo_z = required_topo_z_split(snapshot)
        if tie_k is None:
            tie_k = default_tie_k(snapshot)
        out = run(snapshot, n_groups, features, topo_z, tie_k)
        retrace.note(
            "auction-sharded", run,
            lambda: retrace.signature(
                snapshot, (n_groups, features, topo_z, tie_k, mesh_sig)
            ),
        )
        return out

    call.jitted = run  # raw jit, for AOT prewarm (lower().compile())
    return call
