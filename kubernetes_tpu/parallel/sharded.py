"""Node-axis-sharded solves: the multi-chip scheduling step.

The reference scales its hot loop with 16 goroutines and adaptive node
sampling (parallelize/parallelism.go, schedule_one.go:662); the TPU-native
scale-out shards the *node axis* of every cluster tensor across a device
mesh with shard_map.  Each chip filters and scores its node shard, reduces
its local champion, and a pmax/pmin pair elects the global winner — the
ring-reduction analogue sketched in SURVEY.md section 5.7.  The winning
shard applies the assume-update locally; per-pod state (requested, ports)
never leaves its shard, so per-step communication is O(1) scalars on ICI
(plus the wavefront's O(K) merged candidate list per wave), independent
of cluster size.

All three solver families follow the ops.auction pattern — ONE
implementation, two layouts: ops.assign.greedy_assign /
wavefront_assign and ops.auction.auction_assign take an ``axis_name``
and internally switch their node-axis boundary crossings to
ownership-masked psums, pmax/pmin elections, and all_gather merges.
The wrappers here only set up the shard_map specs, so the sharded
solvers cannot drift from the single-chip ones.

Tie-break parity with the single-chip path: lowest node index among
max-score nodes (argmax-first-index locally, pmin on the winner index
globally).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """jax.shard_map graduated from jax.experimental after 0.4.x and
    renamed check_rep to check_vma; accept both APIs so the sharded
    solvers run on either jax generation."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )

from ..analysis import retrace
from ..ops.assign import (
    DEFAULT_WAVE_CAP,
    FeatureFlags,
    SolveResult,
    features_of,
    greedy_assign,
    needs_topo,
    plan_waves,
    required_topo_z,
    required_topo_z_split,
    wavefront_assign,
)
from ..ops.auction import (
    AuctionResult,
    auction_assign,
    auction_features_ok,
    default_tie_k,
)
from ..ops.partials import ClassStatics
from ..ops.schema import (
    ClusterTensors,
    PrefPodTable,
    Snapshot,
    SpreadTable,
    TermTable,
    num_groups,
)
from ..ops.preemption import (
    BatchDryRunResult,
    PreemptionBatch,
    batched_dry_run,
)
from ..ops.scores import DEFAULT_SCORE_CONFIG, ScoreConfig

AXIS = "nodes"

# PartitionSpec for each ClusterTensors field: node axis sharded, the rest
# replicated.  taint_bits is effect-major so its node axis is dim 1.
CLUSTER_SPECS = ClusterTensors(
    allocatable=P(AXIS, None),
    requested=P(AXIS, None),
    nonzero_requested=P(AXIS, None),
    node_valid=P(AXIS),
    name_id=P(AXIS),
    label_bits=P(AXIS, None),
    taint_bits=P(None, AXIS, None),
    port_bits=P(AXIS, None),
    topo_ids=P(AXIS, None),
    image_bits=P(AXIS, None),
    slice_id=P(AXIS),
    torus_coords=P(AXIS, None),
    slice_dims=P(AXIS, None),
    slice_pos=P(AXIS),
)


# Warm-start statics ([C, N] per-class triples gathered from the
# device-resident PartialsCache): node axis sharded like every other
# [·, N] table — the resident store carries exactly this layout, so a
# warm mesh solve consumes it without resharding.
STATICS_SPECS = ClassStatics(
    sfeas=P(None, AXIS), aff=P(None, AXIS), taint=P(None, AXIS)
)


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(devices, (AXIS,))


def mesh_signature(mesh: Mesh) -> tuple:
    """Hashable mesh-shape component of a sharded executable key (the
    retrace tracker's and the prewarm pool's mesh discriminator)."""
    return ("mesh",) + tuple(int(d) for d in mesh.devices.shape)


def _spread_specs(rep):
    return SpreadTable(
        valid=rep, slot=rep, max_skew=rep, min_domains=rep, hard=rep,
        owner_sel_idx=rep, owner_keys=rep, node_matches=P(None, AXIS),
        pod_matches=rep, pod_idx=rep,
    )


def _term_specs(rep):
    return TermTable(
        valid=rep, slot=rep, node_matches=P(None, AXIS),
        node_owners=P(None, AXIS), matches_incoming=rep, aff_idx=rep,
        anti_idx=rep, self_match_all=rep,
    )


def _prefpod_specs(rep):
    return PrefPodTable(
        valid=rep, slot=rep, node_counts=P(None, AXIS),
        owner_weight=P(None, AXIS), matches_incoming=rep, pod_idx=rep,
        pod_weight=rep,
    )


def _snapshot_in_specs(parts):
    """shard_map in_specs for the 8 Snapshot components: cluster tensors
    node-sharded, pod/constraint tables replicated except their [·, N]
    per-node count matrices."""
    rep = P()
    (cluster, pods, sel, pref, spread, terms, prefpod, images) = parts
    return (
        CLUSTER_SPECS,
        jax.tree.map(lambda _: rep, pods),
        jax.tree.map(lambda _: rep, sel),
        jax.tree.map(lambda _: rep, pref),
        _spread_specs(rep),
        _term_specs(rep),
        _prefpod_specs(rep),
        jax.tree.map(lambda _: rep, images),
    )


def _check_divisible(n: int, mesh: Mesh) -> None:
    n_dev = mesh.devices.size
    if n % n_dev:
        raise ValueError(
            f"padded node count {n} not divisible by mesh size {n_dev}"
        )


def sharded_greedy_assign(
    snapshot: Snapshot,
    mesh: Mesh,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    topo_z: Optional[int] = None,
    features: Optional[FeatureFlags] = None,
    n_groups: int = 0,
    statics: Optional[ClassStatics] = None,
) -> SolveResult:
    """greedy_assign with the node axis sharded over `mesh`.

    Placement semantics are identical to ops.assign.greedy_assign; only
    the data layout differs — this wrapper sets up shard_map specs and
    calls greedy_assign(axis_name=...), which handles the elections and
    constraint-state broadcasts internally.  Requires the padded node
    count to be divisible by the mesh size (SnapshotBuilder pads to
    powers of two, mesh sizes are powers of two, so this holds whenever
    the cluster bucket is at least one row per chip;
    TPUBatchScheduler._dispatch falls back to the single chip — counted
    in `sharded_solve_fallbacks` — otherwise).

    Constraint count state ([C/T, Z]) is small and kept replicated: each
    shard scatter-builds counts from its node shard, a psum replicates
    them, and per-placement updates are broadcast from the winning
    shard.  Gang all-or-nothing (n_groups) runs the shared post-pass
    with per-shard ownership masking."""
    if features is None:
        features = features_of(snapshot)
    if topo_z is None:
        topo_z = required_topo_z(snapshot)
    parts = jax.tree.map(jnp.asarray, tuple(snapshot))
    _check_divisible(parts[0].allocatable.shape[0], mesh)

    rep = P()
    slice_specs = (
        {
            "frag_score": rep, "carveouts": rep,
            "contiguous_gangs": rep, "carveout_fallbacks": rep,
        }
        if features.slices
        else {}
    )
    out_specs = SolveResult(
        assignment=rep, scores=rep, feasible_counts=rep,
        cluster=CLUSTER_SPECS, reasons=rep, **slice_specs,
    )

    if statics is None:

        @partial(
            _shard_map,
            mesh=mesh,
            in_specs=_snapshot_in_specs(parts),
            out_specs=out_specs,
            check_vma=False,
        )
        def run(cl, pods, sel, pref, spread, terms, prefpod, images):
            local = Snapshot(
                cl, pods, sel, pref, spread, terms, prefpod, images
            )
            return greedy_assign(
                local, cfg, topo_z=topo_z, features=features,
                n_groups=n_groups, axis_name=AXIS,
            )

        return run(*parts)

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_snapshot_in_specs(parts) + (STATICS_SPECS,),
        out_specs=out_specs,
        check_vma=False,
    )
    def run_warm(cl, pods, sel, pref, spread, terms, prefpod, images, st):
        local = Snapshot(cl, pods, sel, pref, spread, terms, prefpod, images)
        return greedy_assign(
            local, cfg, topo_z=topo_z, features=features,
            n_groups=n_groups, axis_name=AXIS, statics=st,
        )

    return run_warm(*parts, jax.tree.map(jnp.asarray, statics))


def sharded_wavefront_assign(
    snapshot: Snapshot,
    wave_members,
    mesh: Mesh,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    topo_z: Optional[int] = None,
    features: Optional[FeatureFlags] = None,
    n_groups: int = 0,
    statics: Optional[ClassStatics] = None,
) -> SolveResult:
    """wavefront_assign with the node axis sharded over `mesh` — the
    production mesh route for large greedy batches: ~P/W wave steps
    instead of P, each wave evaluated on all chips in parallel.

    The wave plan stays a replicated host-side device argument
    (plan_waves — pod-space only), the batched [K, N] evaluation runs
    per shard, the top-(K+1) candidate lists merge through one
    all_gather per wave, and the O(K) mini-scan corrections are computed
    on psum-replicated picked rows so every shard reaches the same
    choice without per-pod elections (see wavefront_assign's axis_name
    docstring).  Placements — and the serialized-wave / fit-flip
    fallback counters — are bit-identical to the single-chip wavefront,
    which is itself scan-identical."""
    if features is None:
        features = features_of(snapshot)
    if topo_z is None:
        topo_z = required_topo_z(snapshot)
    parts = jax.tree.map(jnp.asarray, tuple(snapshot))
    _check_divisible(parts[0].allocatable.shape[0], mesh)
    members = jnp.asarray(wave_members, jnp.int32)

    rep = P()
    out_specs = SolveResult(
        assignment=rep, scores=rep, feasible_counts=rep,
        cluster=CLUSTER_SPECS, reasons=rep, wave_count=rep,
        wave_fallbacks=rep,
    )

    if statics is None:

        @partial(
            _shard_map,
            mesh=mesh,
            in_specs=_snapshot_in_specs(parts) + (rep,),
            out_specs=out_specs,
            check_vma=False,
        )
        def run(cl, pods, sel, pref, spread, terms, prefpod, images, mem):
            local = Snapshot(
                cl, pods, sel, pref, spread, terms, prefpod, images
            )
            return wavefront_assign(
                local, mem, cfg, topo_z=topo_z, features=features,
                n_groups=n_groups, axis_name=AXIS,
            )

        return run(*parts, members)

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_snapshot_in_specs(parts) + (rep, STATICS_SPECS),
        out_specs=out_specs,
        check_vma=False,
    )
    def run_warm(cl, pods, sel, pref, spread, terms, prefpod, images, mem, st):
        local = Snapshot(cl, pods, sel, pref, spread, terms, prefpod, images)
        return wavefront_assign(
            local, mem, cfg, topo_z=topo_z, features=features,
            n_groups=n_groups, axis_name=AXIS, statics=st,
        )

    return run_warm(*parts, members, jax.tree.map(jnp.asarray, statics))


def sharded_auction_assign(
    snapshot: Snapshot,
    mesh: Mesh,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    n_groups: int = 0,
    tie_seed: int = 0,
    max_rounds: int = 64,
    features: Optional[FeatureFlags] = None,
    topo_z=None,
    tie_k: Optional[int] = None,
) -> AuctionResult:
    """auction_assign with the node axis sharded over `mesh` — the
    multi-chip joint solve (the north-star gang-burst config at scales
    one chip's HBM can't hold).

    One implementation, two layouts: this wrapper only sets up
    shard_map specs and calls ops.auction.auction_assign(axis_name=...)
    — pod-space state is replicated, node-space state sharded, and the
    boundary crossings are ownership-masked psums, a pmax/pmin election,
    and an all_gather tie-set merge (see auction_assign's docstring).
    Placements are bit-identical to the single-chip auction.
    """
    if features is None:
        features = features_of(snapshot)
    if not auction_features_ok(features):
        raise ValueError(
            "auction does not cover in-batch host ports or "
            "affinity-direction inter-pod terms; route through "
            "sharded_greedy_assign"
        )
    if topo_z is None:
        topo_z = required_topo_z_split(snapshot)
    if tie_k is None:
        tie_k = default_tie_k(snapshot)
    parts = jax.tree.map(jnp.asarray, tuple(snapshot))
    n = parts[0].allocatable.shape[0]
    _check_divisible(n, mesh)
    # tie_k bounds the GLOBAL tie list; each shard's local top_k clamps
    # to its shard size inside auction_assign and the all_gather merge
    # restores the global length
    tie_k = min(tie_k, n)

    rep = P()
    out_specs = AuctionResult(
        assignment=rep, scores=rep, rounds=rep, gang_dropped=rep,
        cluster=CLUSTER_SPECS, reasons=rep,
        debug_sp_counts=P(None, AXIS) if features.spread else None,
    )

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_snapshot_in_specs(parts),
        out_specs=out_specs,
        check_vma=False,
    )
    def run(cl, pods, sel, pref, spread, terms, prefpod, images):
        local = Snapshot(cl, pods, sel, pref, spread, terms, prefpod, images)
        return auction_assign(
            local, cfg, n_groups=n_groups, tie_seed=tie_seed,
            max_rounds=max_rounds, features=features, topo_z=topo_z,
            tie_k=tie_k, axis_name=AXIS,
        )

    return run(*parts)


# -- jitted wrappers ---------------------------------------------------------
#
# Mirrors of ops.assign's *_jit closures for the mesh layout: one
# executable per (shape bucket, statics, MESH SHAPE).  Every dispatch
# reports to the recompile-discipline tracker (analysis/retrace.py) with
# the mesh shape folded into the signature — a mesh-mode batch must
# never silently compile a fresh executable in steady state.  `.jitted`
# exposes the raw jit for the prewarm pool's AOT lower().compile().


def sharded_greedy_jit(mesh: Mesh, cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    mesh_sig = mesh_signature(mesh)

    @partial(jax.jit, static_argnums=(1, 2, 3))
    def run(
        snapshot: Snapshot, topo_z: int, features: FeatureFlags,
        n_groups: int,
    ) -> SolveResult:
        return sharded_greedy_assign(
            snapshot, mesh, cfg, topo_z=topo_z, features=features,
            n_groups=n_groups,
        )

    @partial(jax.jit, static_argnums=(2, 3, 4))
    def run_warm(
        snapshot: Snapshot, statics, topo_z: int, features: FeatureFlags,
        n_groups: int,
    ) -> SolveResult:
        return sharded_greedy_assign(
            snapshot, mesh, cfg, topo_z=topo_z, features=features,
            n_groups=n_groups, statics=statics,
        )

    def call(
        snapshot: Snapshot,
        topo_z: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
        n_groups: Optional[int] = None,
        statics=None,
    ) -> SolveResult:
        if features is None:
            features = features_of(snapshot)
        if topo_z is None:
            topo_z = (
                required_topo_z(snapshot) if needs_topo(features) else 1
            )
        if n_groups is None:
            n_groups = num_groups(snapshot)
        if n_groups > 0:
            from ..utils.vocab import pad_dim

            n_groups = pad_dim(n_groups, 1)
        if statics is not None:
            out = run_warm(snapshot, statics, topo_z, features, n_groups)
            retrace.note(
                "greedy-sharded-warm", run_warm,
                lambda: retrace.signature(
                    (snapshot, statics),
                    (topo_z, features, n_groups, mesh_sig),
                ),
            )
            return out
        out = run(snapshot, topo_z, features, n_groups)
        retrace.note(
            "greedy-sharded", run,
            lambda: retrace.signature(
                snapshot, (topo_z, features, n_groups, mesh_sig)
            ),
        )
        return out

    call.jitted = run  # raw jit, for AOT prewarm (lower().compile())
    call.jitted_warm = run_warm
    return call


def sharded_wavefront_jit(mesh: Mesh, cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    """Jitted sharded wavefront: one executable per (shape bucket,
    topo_z, features, n_groups, wave shape, mesh shape).  The wave plan
    stays a device argument so repartitions reuse the executable."""
    mesh_sig = mesh_signature(mesh)

    @partial(jax.jit, static_argnums=(2, 3, 4))
    def run(
        snapshot: Snapshot, wave_members, topo_z: int,
        features: FeatureFlags, n_groups: int,
    ) -> SolveResult:
        return sharded_wavefront_assign(
            snapshot, wave_members, mesh, cfg, topo_z=topo_z,
            features=features, n_groups=n_groups,
        )

    @partial(jax.jit, static_argnums=(3, 4, 5))
    def run_warm(
        snapshot: Snapshot, wave_members, statics, topo_z: int,
        features: FeatureFlags, n_groups: int,
    ) -> SolveResult:
        return sharded_wavefront_assign(
            snapshot, wave_members, mesh, cfg, topo_z=topo_z,
            features=features, n_groups=n_groups, statics=statics,
        )

    def call(
        snapshot: Snapshot,
        wave_members=None,
        topo_z: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
        n_groups: Optional[int] = None,
        wave_cap: int = DEFAULT_WAVE_CAP,
        statics=None,
    ) -> SolveResult:
        if features is None:
            features = features_of(snapshot)
        if topo_z is None:
            topo_z = (
                required_topo_z(snapshot) if needs_topo(features) else 1
            )
        if n_groups is None:
            n_groups = num_groups(snapshot)
        if n_groups > 0:
            from ..utils.vocab import pad_dim

            n_groups = pad_dim(n_groups, 1)
        if wave_members is None:
            wave_members = plan_waves(
                snapshot, features=features, wave_cap=wave_cap
            ).members
        members = jnp.asarray(wave_members, jnp.int32)
        if statics is not None:
            out = run_warm(snapshot, members, statics, topo_z, features,
                           n_groups)
            retrace.note(
                "wavefront-sharded-warm", run_warm,
                lambda: retrace.signature(
                    (snapshot, members, statics),
                    (topo_z, features, n_groups, mesh_sig),
                ),
            )
            return out
        out = run(snapshot, members, topo_z, features, n_groups)
        retrace.note(
            "wavefront-sharded", run,
            lambda: retrace.signature(
                (snapshot, members), (topo_z, features, n_groups, mesh_sig)
            ),
        )
        return out

    call.jitted = run  # raw jit, for AOT prewarm (lower().compile())
    call.jitted_warm = run_warm
    return call


def sharded_auction_jit(mesh: Mesh, cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    mesh_sig = mesh_signature(mesh)

    @partial(jax.jit, static_argnums=(1, 2, 3, 4))
    def run(snapshot, n_groups, features, topo_z, tie_k):
        return sharded_auction_assign(
            snapshot, mesh, cfg, n_groups=n_groups, features=features,
            topo_z=topo_z, tie_k=tie_k,
        )

    def call(
        snapshot: Snapshot,
        n_groups: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
        topo_z=None,
        tie_k: Optional[int] = None,
    ) -> AuctionResult:
        if features is None:
            features = features_of(snapshot)
        if n_groups is None:
            n_groups = num_groups(snapshot)
        if topo_z is None:
            topo_z = required_topo_z_split(snapshot)
        if tie_k is None:
            tie_k = default_tie_k(snapshot)
        out = run(snapshot, n_groups, features, topo_z, tie_k)
        retrace.note(
            "auction-sharded", run,
            lambda: retrace.signature(
                snapshot, (n_groups, features, topo_z, tie_k, mesh_sig)
            ),
        )
        return out

    call.jitted = run  # raw jit, for AOT prewarm (lower().compile())
    return call


# -- pod-axis sharding -------------------------------------------------------
#
# The node axis has been elastic since the mesh wrappers above; the POD
# axis is the other long dimension of a 12k+ pods/s burst, and three
# kernels are wide on it: the wavefront's per-wave [K, N] evaluation
# (K members per wave), and the PostFilter pass's [P, N] batched
# dry-run / static-feasibility sweeps.  These twins shard THAT axis:
# node tensors stay replicated (they fit — the node mesh exists for the
# opposite regime), each device evaluates its contiguous pod/member
# block, and the only boundary crossing is one all_gather of the
# per-pod result rows.  Placements are bit-identical to the
# single-shard kernels: the wavefront runs its top-k/mini-scan math
# replicated after the gather (see wavefront_assign's pod_axis_name
# docstring), and the preemption kernels are pod-row independent, so a
# row block computed locally IS the global row slice.

POD_AXIS = "pods"


def make_pod_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(devices, (POD_AXIS,))


def _check_divisible_pods(p: int, mesh: Mesh, what: str) -> None:
    n_dev = mesh.devices.size
    if p % n_dev:
        raise ValueError(
            f"{what} {p} not divisible by pod-mesh size {n_dev}"
        )


def pad_wave_columns(wave_members, mesh: Mesh) -> np.ndarray:
    """Pad the wave plan's member axis with -1 columns to a multiple of
    the pod-mesh size.  -1 members are the same inert pads plan_waves
    already emits for ragged waves — masked out of every eval, dropped
    by the out-of-bounds final scatter — so padded plans place
    identically to the originals."""
    members = np.asarray(wave_members, np.int32)
    d = mesh.devices.size
    pad = (-members.shape[1]) % d
    if pad:
        members = np.concatenate(
            [members, np.full((members.shape[0], pad), -1, np.int32)],
            axis=1,
        )
    return members


def podsharded_wavefront_assign(
    snapshot: Snapshot,
    wave_members,
    mesh: Mesh,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    topo_z: Optional[int] = None,
    features: Optional[FeatureFlags] = None,
    n_groups: int = 0,
    statics: Optional[ClassStatics] = None,
) -> SolveResult:
    """wavefront_assign with the WAVE-MEMBER axis sharded over `mesh` —
    the twin of sharded_wavefront_assign for the wide-batch/modest-node
    regime, where waves are K-wide but every chip can hold the full
    cluster: each device evaluates K/D members per wave against the
    replicated node tables, one all_gather per wave rebuilds the [K, N]
    score block, and the candidate merge / wave-safety / mini-scan math
    runs replicated-identically everywhere (no elections, node offset
    0).  Pads the member axis with inert -1 columns when K is not
    divisible by the mesh size.  Placements are bit-identical to the
    single-chip wavefront."""
    if features is None:
        features = features_of(snapshot)
    if topo_z is None:
        topo_z = required_topo_z(snapshot)
    parts = jax.tree.map(jnp.asarray, tuple(snapshot))
    # pad with jnp so the wrapper also traces under the jitted dispatch
    # (the K axis is static, so the pad width is a Python int either way)
    members = jnp.asarray(wave_members, jnp.int32)
    pad = (-members.shape[1]) % mesh.devices.size
    if pad:
        members = jnp.concatenate(
            [
                members,
                jnp.full((members.shape[0], pad), -1, jnp.int32),
            ],
            axis=1,
        )

    rep = P()
    rep_parts = tuple(jax.tree.map(lambda _: rep, part) for part in parts)
    rep_cluster = ClusterTensors(*([rep] * len(CLUSTER_SPECS)))
    out_specs = SolveResult(
        assignment=rep, scores=rep, feasible_counts=rep,
        cluster=rep_cluster, reasons=rep, wave_count=rep,
        wave_fallbacks=rep,
    )

    if statics is None:

        @partial(
            _shard_map,
            mesh=mesh,
            in_specs=rep_parts + (P(None, POD_AXIS),),
            out_specs=out_specs,
            check_vma=False,
        )
        def run(cl, pods, sel, pref, spread, terms, prefpod, images, mem):
            local = Snapshot(
                cl, pods, sel, pref, spread, terms, prefpod, images
            )
            return wavefront_assign(
                local, mem, cfg, topo_z=topo_z, features=features,
                n_groups=n_groups, pod_axis_name=POD_AXIS,
            )

        return run(*parts, members)

    statics_rep = ClassStatics(sfeas=rep, aff=rep, taint=rep)

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=rep_parts + (P(None, POD_AXIS), statics_rep),
        out_specs=out_specs,
        check_vma=False,
    )
    def run_warm(cl, pods, sel, pref, spread, terms, prefpod, images, mem, st):
        local = Snapshot(cl, pods, sel, pref, spread, terms, prefpod, images)
        return wavefront_assign(
            local, mem, cfg, topo_z=topo_z, features=features,
            n_groups=n_groups, pod_axis_name=POD_AXIS, statics=st,
        )

    return run_warm(*parts, members, jax.tree.map(jnp.asarray, statics))


def podsharded_wavefront_jit(
    mesh: Mesh, cfg: ScoreConfig = DEFAULT_SCORE_CONFIG
):
    """Jitted pod-sharded wavefront: one executable per (shape bucket,
    topo_z, features, n_groups, wave shape, mesh shape), same discipline
    as sharded_wavefront_jit."""
    mesh_sig = mesh_signature(mesh)

    @partial(jax.jit, static_argnums=(2, 3, 4))
    def run(
        snapshot: Snapshot, wave_members, topo_z: int,
        features: FeatureFlags, n_groups: int,
    ) -> SolveResult:
        return podsharded_wavefront_assign(
            snapshot, wave_members, mesh, cfg, topo_z=topo_z,
            features=features, n_groups=n_groups,
        )

    @partial(jax.jit, static_argnums=(3, 4, 5))
    def run_warm(
        snapshot: Snapshot, wave_members, statics, topo_z: int,
        features: FeatureFlags, n_groups: int,
    ) -> SolveResult:
        return podsharded_wavefront_assign(
            snapshot, wave_members, mesh, cfg, topo_z=topo_z,
            features=features, n_groups=n_groups, statics=statics,
        )

    def call(
        snapshot: Snapshot,
        wave_members=None,
        topo_z: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
        n_groups: Optional[int] = None,
        wave_cap: int = DEFAULT_WAVE_CAP,
        statics=None,
    ) -> SolveResult:
        if features is None:
            features = features_of(snapshot)
        if topo_z is None:
            topo_z = (
                required_topo_z(snapshot) if needs_topo(features) else 1
            )
        if n_groups is None:
            n_groups = num_groups(snapshot)
        if n_groups > 0:
            from ..utils.vocab import pad_dim

            n_groups = pad_dim(n_groups, 1)
        if wave_members is None:
            wave_members = plan_waves(
                snapshot, features=features, wave_cap=wave_cap
            ).members
        members = jnp.asarray(pad_wave_columns(wave_members, mesh))
        if statics is not None:
            out = run_warm(snapshot, members, statics, topo_z, features,
                           n_groups)
            retrace.note(
                "wavefront-podsharded-warm", run_warm,
                lambda: retrace.signature(
                    (snapshot, members, statics),
                    (topo_z, features, n_groups, mesh_sig),
                ),
            )
            return out
        out = run(snapshot, members, topo_z, features, n_groups)
        retrace.note(
            "wavefront-podsharded", run,
            lambda: retrace.signature(
                (snapshot, members), (topo_z, features, n_groups, mesh_sig)
            ),
        )
        return out

    call.jitted = run  # raw jit, for AOT prewarm (lower().compile())
    call.jitted_warm = run_warm
    return call


def sharded_batched_dry_run(
    batch: PreemptionBatch, mesh: Mesh
) -> BatchDryRunResult:
    """batched_dry_run with the PREEMPTOR axis sharded over `mesh`: the
    per-node victim tensors (free/victim_req/perm/elig_len/viol) stay
    replicated — each shard redundantly recomputes the per-LEVEL
    cumulative eviction tensors, which are shared across pods anyway —
    and the [P, N, K+1] broadcast fit test, the dominant term, runs on
    P/D pod rows per device.  Every row is computed exactly as in the
    single-shard kernel (pure per-pod gathers), so the stitched [P, N]
    result is bit-identical."""
    parts = jax.tree.map(jnp.asarray, batch)
    _check_divisible_pods(
        int(parts.pods_req.shape[0]), mesh, "preemptor count"
    )

    rep = P()
    in_specs = PreemptionBatch(
        free=rep, victim_req=rep, perm=rep, elig_len=rep, viol=rep,
        pods_req=P(POD_AXIS, None), pod_level=P(POD_AXIS),
    )
    out_specs = BatchDryRunResult(
        feasible=P(POD_AXIS, None), min_k=P(POD_AXIS, None),
        viol_k=P(POD_AXIS, None),
    )

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(in_specs,),
        out_specs=out_specs,
        check_vma=False,
    )
    def run(b):
        return batched_dry_run(b)

    return run(parts)


def sharded_static_feasible_batch(
    cluster, pods, selectors, mesh: Mesh
) -> jnp.ndarray:
    """static_feasible_batch with the preemptor axis sharded: the
    PodBatch stays replicated (pod views gather class/spec rows from
    shared tables, so slicing the structure itself would tear them) and
    each device evaluates its contiguous index block, axis_index-offset
    into the global pod range.  Output rows are bit-identical to the
    single-shard sweep."""
    from ..ops.filters import (
        pod_view,
        selector_match,
        static_feasible_for_pod,
    )

    p = int(pods.req.shape[0])
    _check_divisible_pods(p, mesh, "preemptor count")
    p_local = p // mesh.devices.size

    rep = P()
    in_specs = tuple(
        jax.tree.map(lambda _: rep, part)
        for part in (cluster, pods, selectors)
    )

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(POD_AXIS, None),
        check_vma=False,
    )
    def run(cl, pd, sel):
        sel_mask = selector_match(cl, sel)
        i0 = jax.lax.axis_index(POD_AXIS) * p_local

        def one(i):
            return static_feasible_for_pod(cl, pod_view(pd, i), sel_mask)

        return jax.vmap(one)(i0 + jnp.arange(p_local, dtype=jnp.int32))

    return run(
        jax.tree.map(jnp.asarray, cluster),
        jax.tree.map(jnp.asarray, pods),
        jax.tree.map(jnp.asarray, selectors),
    )
