"""Node-axis-sharded greedy solve: the multi-chip scheduling step.

The reference scales its hot loop with 16 goroutines and adaptive node
sampling (parallelize/parallelism.go, schedule_one.go:662); the TPU-native
scale-out shards the *node axis* of every cluster tensor across a device
mesh with shard_map.  Each chip filters and scores its node shard, reduces
its local champion, and a pmax/pmin pair elects the global winner — the
ring-reduction analogue sketched in SURVEY.md section 5.7.  The winning
shard applies the assume-update locally; per-pod state (requested, ports)
never leaves its shard, so per-step communication is O(1) scalars on ICI,
independent of cluster size.

Tie-break parity with the single-chip path: lowest node index among
max-score nodes (argmax-first-index locally, pmin on the winner index
globally).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """jax.shard_map graduated from jax.experimental after 0.4.x and
    renamed check_rep to check_vma; accept both APIs so the sharded
    solvers run on either jax generation."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )

from ..ops.assign import (
    NEG_INF,
    FeatureFlags,
    SolveResult,
    class_statics,
    features_of,
    needs_topo,
    required_topo_z,
    required_topo_z_split,
    solve_order,
)
from ..ops.auction import (
    AuctionResult,
    auction_assign,
    auction_features_ok,
    default_tie_k,
)
from ..ops.filters import (
    fits_resources,
    pod_view,
    preferred_match,
    selector_match,
)
from ..ops.interpod import (
    interpod_filter,
    interpod_update,
    prep_pref_pod,
    prep_terms,
)
from ..ops.schema import (
    ClusterTensors,
    ImageTable,
    PrefPodTable,
    Snapshot,
    SpreadTable,
    TermTable,
    num_groups,
)
from ..ops.scores import (
    DEFAULT_SCORE_CONFIG,
    ScoreConfig,
    score_from_raw,
    static_extra,
)
from ..ops.topology import prep_spread, spread_filter, spread_score, spread_update

AXIS = "nodes"

# PartitionSpec for each ClusterTensors field: node axis sharded, the rest
# replicated.  taint_bits is effect-major so its node axis is dim 1.
CLUSTER_SPECS = ClusterTensors(
    allocatable=P(AXIS, None),
    requested=P(AXIS, None),
    nonzero_requested=P(AXIS, None),
    node_valid=P(AXIS),
    name_id=P(AXIS),
    label_bits=P(AXIS, None),
    taint_bits=P(None, AXIS, None),
    port_bits=P(AXIS, None),
    topo_ids=P(AXIS, None),
    image_bits=P(AXIS, None),
)


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(devices, (AXIS,))


def _spread_specs(rep):
    return SpreadTable(
        valid=rep, slot=rep, max_skew=rep, min_domains=rep, hard=rep,
        owner_sel_idx=rep, owner_keys=rep, node_matches=P(None, AXIS),
        pod_matches=rep, pod_idx=rep,
    )


def _term_specs(rep):
    return TermTable(
        valid=rep, slot=rep, node_matches=P(None, AXIS),
        node_owners=P(None, AXIS), matches_incoming=rep, aff_idx=rep,
        anti_idx=rep, self_match_all=rep,
    )


def _prefpod_specs(rep):
    return PrefPodTable(
        valid=rep, slot=rep, node_counts=P(None, AXIS),
        owner_weight=P(None, AXIS), matches_incoming=rep, pod_idx=rep,
        pod_weight=rep,
    )


def _broadcast_column(matrix: jnp.ndarray, local_idx: jnp.ndarray, own: jnp.ndarray):
    """Give every shard the owning shard's matrix[:, local_idx] column
    (psum of a single masked contribution)."""
    col = jnp.where(own, matrix[:, local_idx], 0)
    return jax.lax.psum(col, AXIS)


def sharded_greedy_assign(
    snapshot: Snapshot,
    mesh: Mesh,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    topo_z: Optional[int] = None,
    features: Optional[FeatureFlags] = None,
) -> SolveResult:
    """greedy_assign with the node axis sharded over `mesh`.

    Placement semantics are identical to ops.assign.greedy_assign; only the
    data layout differs.  Requires the padded node count to be divisible by
    the mesh size (SnapshotBuilder pads to powers of two, mesh sizes are
    powers of two, so this holds by construction).

    Constraint count state ([C/T, Z]) is small and kept replicated: each
    shard scatter-builds counts from its node shard, a psum replicates
    them, and per-placement updates are broadcast from the winning shard.
    """
    if features is None:
        features = features_of(snapshot)
    if topo_z is None:
        topo_z = required_topo_z(snapshot)
    (cluster, pods, sel, pref, spread, terms, prefpod, images) = jax.tree.map(
        jnp.asarray, tuple(snapshot)
    )
    n = cluster.allocatable.shape[0]
    n_dev = mesh.devices.size
    if n % n_dev:
        raise ValueError(f"padded node count {n} not divisible by mesh size {n_dev}")
    p = pods.req.shape[0]

    rep = P()
    in_specs = (
        CLUSTER_SPECS,
        jax.tree.map(lambda _: rep, pods),
        jax.tree.map(lambda _: rep, sel),
        jax.tree.map(lambda _: rep, pref),
        _spread_specs(rep),
        _term_specs(rep),
        _prefpod_specs(rep),
        jax.tree.map(lambda _: rep, images),
    )
    out_specs = SolveResult(
        assignment=rep, scores=rep, feasible_counts=rep, cluster=CLUSTER_SPECS
    )

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    def run(
        cl: ClusterTensors, pods, sel, pref, spread, terms, prefpod, images
    ) -> SolveResult:
        n_local = cl.allocatable.shape[0]
        offset = jax.lax.axis_index(AXIS) * n_local
        sel_mask = selector_match(cl, sel)
        pref_mask = preferred_match(cl, pref)
        # Hoisted per-class statics over the local node shard ([C, N/k]);
        # normalization maxima stay per-step (they span shards via pmax).
        sfeas_c, aff_c, taint_c = class_statics(cl, pods, sel_mask, pref_mask)
        c_dim = sfeas_c.shape[0]
        order = solve_order(pods)

        # Local scatter + psum => replicated counts over all shards;
        # v/eligible/blocked stay node-sharded.
        sp0 = tm0 = None
        if features.spread:
            sp0 = prep_spread(
                cl, sel_mask, spread, topo_z, axis_name=AXIS,
                has_bound=features.bound_spread,
            )
        if features.interpod:
            tm0 = prep_terms(
                cl, terms, topo_z, axis_name=AXIS, slots=features.term_slots,
                has_bound=features.bound_terms,
            )
        extra_c = None
        if features.interpod_pref or features.images:
            # hoisted per-class extras over the LOCAL node shard; the
            # preps/normalizers span shards via psum/pmax (same hoist as
            # ops.assign's — shared scores.static_extra keeps them from
            # drifting)
            pp = (
                prep_pref_pod(
                    cl, prefpod, topo_z, axis_name=AXIS,
                    has_bound=features.bound_pref,
                )
                if features.interpod_pref
                else None
            )
            reps_e = jnp.clip(pods.class_rep, 0, p - 1)
            extra_c = jax.vmap(
                lambda c, rep: static_extra(
                    cl, prefpod, images, features, cfg, rep, sfeas_c[c],
                    pp, axis_name=AXIS,
                )
            )(jnp.arange(c_dim, dtype=jnp.int32), reps_e)

        def step(carry, k):
            requested, nonzero, new_ports, sp_counts, tm_present, tm_blocked, tm_global = carry
            i = order[k]
            cur = cl._replace(requested=requested, nonzero_requested=nonzero)
            pod = pod_view(pods, i)
            cls = jnp.clip(pods.class_id[i], 0, c_dim - 1)
            feas = sfeas_c[cls] & fits_resources(cur, pod)
            if features.ports:
                feas = feas & ~((new_ports & pod.port_bits[None, :]).any(axis=-1))
            sp = tm = None
            if features.spread:
                sp = sp0._replace(counts_node=sp_counts)
                feas = feas & spread_filter(sp, spread, i, axis_name=AXIS)
            if features.interpod:
                tm = tm0._replace(
                    present_bits=tm_present, blocked_bits=tm_blocked,
                    global_any=tm_global,
                )
                feas = feas & interpod_filter(tm, terms, i)
            sp_score = (
                spread_score(sp, spread, i, feas, axis_name=AXIS)
                if features.soft_spread
                else None
            )
            scores = score_from_raw(
                cur, pod, feas, aff_c[cls], taint_c[cls], cfg,
                axis_name=AXIS, spread_score=sp_score,
                extra=extra_c[cls] if extra_c is not None else None,
            )
            masked = jnp.where(feas, scores, NEG_INF)

            # Local champion, then a 2-collective global election.
            li = jnp.argmax(masked)
            lv = masked[li]
            gi = (offset + li).astype(jnp.int32)
            best = jax.lax.pmax(lv, AXIS)
            cand = jnp.where(lv == best, gi, jnp.int32(2**31 - 1))
            winner = jax.lax.pmin(cand, AXIS)
            found = best > NEG_INF
            idx = jnp.where(found, winner, -1).astype(jnp.int32)

            onehot = ((jnp.arange(n_local) + offset) == winner) & found
            requested = requested + onehot[:, None] * pod.req[None, :]
            nonzero = nonzero + onehot[:, None] * pod.nonzero_req[None, :]
            if features.ports:
                new_ports = jnp.where(
                    onehot[:, None], new_ports | pod.port_bits[None, :], new_ports
                )

            own = found & (winner >= offset) & (winner < offset + n_local)
            wli = jnp.clip(winner - offset, 0, n_local - 1)
            if features.spread:
                sp_v = _broadcast_column(sp.v, wli, own)
                sp_elig = _broadcast_column(sp.eligible.astype(jnp.int32), wli, own) > 0
                sp = spread_update(sp, spread, i, sp_v, sp_elig, found)
                sp_counts = sp.counts_node
            if features.interpod:
                topo_at = _broadcast_column(cl.topo_ids.T, wli, own)
                tm = interpod_update(
                    tm, terms, i, topo_at, found, slots=features.term_slots
                )
                tm_present, tm_blocked, tm_global = (
                    tm.present_bits, tm.blocked_bits, tm.global_any
                )

            n_feas = jax.lax.psum(feas.sum().astype(jnp.int32), AXIS)
            carry = (requested, nonzero, new_ports, sp_counts, tm_present, tm_blocked, tm_global)
            return carry, (i, idx, jnp.where(found, best, NEG_INF), n_feas)

        zero = jnp.zeros(())
        init = (
            cl.requested, cl.nonzero_requested,
            jnp.zeros_like(cl.port_bits) if features.ports else zero,
            sp0.counts_node if features.spread else zero,
            tm0.present_bits if features.interpod else zero,
            tm0.blocked_bits if features.interpod else zero,
            tm0.global_any if features.interpod else zero,
        )
        (requested, nonzero, new_ports, *_rest), (pod_is, assign_o, win_o, nf_o) = (
            jax.lax.scan(step, init, jnp.arange(p))
        )
        assignment = jnp.full(p, -1, jnp.int32).at[pod_is].set(assign_o)
        win = jnp.full(p, NEG_INF).at[pod_is].set(win_o)
        nf = jnp.zeros(p, jnp.int32).at[pod_is].set(nf_o)
        final = cl._replace(
            requested=requested,
            nonzero_requested=nonzero,
            port_bits=(cl.port_bits | new_ports) if features.ports else cl.port_bits,
        )
        return SolveResult(assignment, win, nf, final)

    return run(cluster, pods, sel, pref, spread, terms, prefpod, images)


def sharded_auction_assign(
    snapshot: Snapshot,
    mesh: Mesh,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
    n_groups: int = 0,
    tie_seed: int = 0,
    max_rounds: int = 64,
    features: Optional[FeatureFlags] = None,
    topo_z=None,
    tie_k: Optional[int] = None,
) -> AuctionResult:
    """auction_assign with the node axis sharded over `mesh` — the
    multi-chip joint solve (the north-star gang-burst config at scales
    one chip's HBM can't hold).

    One implementation, two layouts: this wrapper only sets up
    shard_map specs and calls ops.auction.auction_assign(axis_name=...)
    — pod-space state is replicated, node-space state sharded, and the
    boundary crossings are ownership-masked psums, a pmax/pmin election,
    and an all_gather tie-set merge (see auction_assign's docstring).
    Placements are bit-identical to the single-chip auction.
    """
    if features is None:
        features = features_of(snapshot)
    if not auction_features_ok(features):
        raise ValueError(
            "auction does not cover in-batch host ports or "
            "affinity-direction inter-pod terms; route through "
            "sharded_greedy_assign"
        )
    if topo_z is None:
        topo_z = required_topo_z_split(snapshot)
    if tie_k is None:
        tie_k = default_tie_k(snapshot)
    (cluster, pods, sel, pref, spread, terms, prefpod, images) = jax.tree.map(
        jnp.asarray, tuple(snapshot)
    )
    n = cluster.allocatable.shape[0]
    n_dev = mesh.devices.size
    if n % n_dev:
        raise ValueError(f"padded node count {n} not divisible by mesh size {n_dev}")
    # tie_k bounds the GLOBAL tie list; each shard's local top_k clamps
    # to its shard size inside auction_assign and the all_gather merge
    # restores the global length
    tie_k = min(tie_k, n)

    rep = P()
    in_specs = (
        CLUSTER_SPECS,
        jax.tree.map(lambda _: rep, pods),
        jax.tree.map(lambda _: rep, sel),
        jax.tree.map(lambda _: rep, pref),
        _spread_specs(rep),
        _term_specs(rep),
        _prefpod_specs(rep),
        jax.tree.map(lambda _: rep, images),
    )
    out_specs = AuctionResult(
        assignment=rep, scores=rep, rounds=rep, gang_dropped=rep,
        cluster=CLUSTER_SPECS, reasons=rep,
        debug_sp_counts=P(None, AXIS) if features.spread else None,
    )

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    def run(cl, pods, sel, pref, spread, terms, prefpod, images):
        local = Snapshot(cl, pods, sel, pref, spread, terms, prefpod, images)
        return auction_assign(
            local, cfg, n_groups=n_groups, tie_seed=tie_seed,
            max_rounds=max_rounds, features=features, topo_z=topo_z,
            tie_k=tie_k, axis_name=AXIS,
        )

    return run(cluster, pods, sel, pref, spread, terms, prefpod, images)


def sharded_auction_jit(mesh: Mesh, cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    @partial(jax.jit, static_argnums=(1, 2, 3, 4))
    def run(snapshot, n_groups, features, topo_z, tie_k):
        return sharded_auction_assign(
            snapshot, mesh, cfg, n_groups=n_groups, features=features,
            topo_z=topo_z, tie_k=tie_k,
        )

    def call(
        snapshot: Snapshot,
        n_groups: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
        topo_z=None,
        tie_k: Optional[int] = None,
    ) -> AuctionResult:
        if features is None:
            features = features_of(snapshot)
        if n_groups is None:
            n_groups = num_groups(snapshot)
        if topo_z is None:
            topo_z = required_topo_z_split(snapshot)
        if tie_k is None:
            tie_k = default_tie_k(snapshot)
        return run(snapshot, n_groups, features, topo_z, tie_k)

    return call


def sharded_greedy_jit(mesh: Mesh, cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    @partial(jax.jit, static_argnums=(1, 2))
    def run(snapshot: Snapshot, topo_z: int, features: FeatureFlags) -> SolveResult:
        return sharded_greedy_assign(
            snapshot, mesh, cfg, topo_z=topo_z, features=features
        )

    def call(
        snapshot: Snapshot,
        topo_z: Optional[int] = None,
        features: Optional[FeatureFlags] = None,
    ) -> SolveResult:
        if features is None:
            features = features_of(snapshot)
        if topo_z is None:
            topo_z = (
                required_topo_z(snapshot) if needs_topo(features) else 1
            )
        return run(snapshot, topo_z, features)

    return call
