"""Node-axis-sharded greedy solve: the multi-chip scheduling step.

The reference scales its hot loop with 16 goroutines and adaptive node
sampling (parallelize/parallelism.go, schedule_one.go:662); the TPU-native
scale-out shards the *node axis* of every cluster tensor across a device
mesh with shard_map.  Each chip filters and scores its node shard, reduces
its local champion, and a pmax/pmin pair elects the global winner — the
ring-reduction analogue sketched in SURVEY.md section 5.7.  The winning
shard applies the assume-update locally; per-pod state (requested, ports)
never leaves its shard, so per-step communication is O(1) scalars on ICI,
independent of cluster size.

Tie-break parity with the single-chip path: lowest node index among
max-score nodes (argmax-first-index locally, pmin on the winner index
globally).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.assign import NEG_INF, SolveResult
from ..ops.filters import (
    feasible_for_pod,
    pod_view,
    preferred_match,
    selector_match,
)
from ..ops.schema import ClusterTensors, Snapshot
from ..ops.scores import DEFAULT_SCORE_CONFIG, ScoreConfig, score_for_pod

AXIS = "nodes"

# PartitionSpec for each ClusterTensors field: node axis sharded, the rest
# replicated.  taint_bits is effect-major so its node axis is dim 1.
CLUSTER_SPECS = ClusterTensors(
    allocatable=P(AXIS, None),
    requested=P(AXIS, None),
    nonzero_requested=P(AXIS, None),
    node_valid=P(AXIS),
    name_id=P(AXIS),
    label_bits=P(AXIS, None),
    taint_bits=P(None, AXIS, None),
    port_bits=P(AXIS, None),
    topo_ids=P(AXIS, None),
)


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(devices, (AXIS,))


def sharded_greedy_assign(
    snapshot: Snapshot,
    mesh: Mesh,
    cfg: ScoreConfig = DEFAULT_SCORE_CONFIG,
) -> SolveResult:
    """greedy_assign with the node axis sharded over `mesh`.

    Placement semantics are identical to ops.assign.greedy_assign; only the
    data layout differs.  Requires the padded node count to be divisible by
    the mesh size (SnapshotBuilder pads to powers of two, mesh sizes are
    powers of two, so this holds by construction).
    """
    cluster, pods, sel, pref = jax.tree.map(jnp.asarray, tuple(snapshot))
    n = cluster.allocatable.shape[0]
    n_dev = mesh.devices.size
    if n % n_dev:
        raise ValueError(f"padded node count {n} not divisible by mesh size {n_dev}")
    p = pods.req.shape[0]

    rep = P()
    in_specs = (
        CLUSTER_SPECS,
        jax.tree.map(lambda _: rep, pods),
        jax.tree.map(lambda _: rep, sel),
        jax.tree.map(lambda _: rep, pref),
    )
    out_specs = SolveResult(
        assignment=rep, scores=rep, feasible_counts=rep, cluster=CLUSTER_SPECS
    )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    def run(cl: ClusterTensors, pods, sel, pref) -> SolveResult:
        n_local = cl.allocatable.shape[0]
        offset = jax.lax.axis_index(AXIS) * n_local
        sel_mask = selector_match(cl, sel)
        pref_mask = preferred_match(cl, pref)

        def step(carry, i):
            requested, nonzero, ports = carry
            cur = cl._replace(
                requested=requested, nonzero_requested=nonzero, port_bits=ports
            )
            pod = pod_view(pods, i)
            feas = feasible_for_pod(cur, pod, sel_mask)
            scores = score_for_pod(cur, pod, feas, pref_mask, cfg, axis_name=AXIS)
            masked = jnp.where(feas, scores, NEG_INF)

            # Local champion, then a 2-collective global election.
            li = jnp.argmax(masked)
            lv = masked[li]
            gi = (offset + li).astype(jnp.int32)
            best = jax.lax.pmax(lv, AXIS)
            cand = jnp.where(lv == best, gi, jnp.int32(2**31 - 1))
            winner = jax.lax.pmin(cand, AXIS)
            found = best > NEG_INF
            idx = jnp.where(found, winner, -1).astype(jnp.int32)

            onehot = ((jnp.arange(n_local) + offset) == winner) & found
            requested = requested + onehot[:, None] * pod.req[None, :]
            nonzero = nonzero + onehot[:, None] * pod.nonzero_req[None, :]
            ports = jnp.where(onehot[:, None], ports | pod.port_bits[None, :], ports)
            n_feas = jax.lax.psum(feas.sum().astype(jnp.int32), AXIS)
            return (requested, nonzero, ports), (idx, jnp.where(found, best, NEG_INF), n_feas)

        init = (cl.requested, cl.nonzero_requested, cl.port_bits)
        (requested, nonzero, ports), (assignment, win, nf) = jax.lax.scan(
            step, init, jnp.arange(p)
        )
        final = cl._replace(requested=requested, nonzero_requested=nonzero, port_bits=ports)
        return SolveResult(assignment, win, nf, final)

    return run(cluster, pods, sel, pref)


def sharded_greedy_jit(mesh: Mesh, cfg: ScoreConfig = DEFAULT_SCORE_CONFIG):
    @jax.jit
    def solve(snapshot: Snapshot) -> SolveResult:
        return sharded_greedy_assign(snapshot, mesh, cfg)

    return solve
