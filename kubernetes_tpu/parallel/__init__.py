"""Multi-chip sharding of the batched solve over a jax.sharding.Mesh."""
