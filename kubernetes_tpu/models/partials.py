"""PartialsCache — device-resident Filter/Score partials warm-started
from the mirror (the incremental O(changes) solve).

The sibling of DeviceClusterMirror: where the mirror makes host→device
TRANSFER O(changed rows), this cache makes the per-batch Filter/Score
RE-EVALUATION O(changes).  It keeps the per-class static triple
(ops/partials.py PartialsStore: static feasibility + raw
affinity/taint score rows) resident on device, keyed by CONTENT
signatures of the encoder's pod classes (schema._pod_classes, with the
batch-local selector/preferred table indices replaced by the builder's
persistent signature registry ids, so the key survives across batches).

Per sync (called under the cache lock from encode_pending, right after
mirror.sync()):

  1. classes already cached re-evaluate ONLY the node rows dirtied
     since the cache's last sync (ClusterState.dirty_rows — this
     includes every row the previous wave's picks touched, since
     assumes bump the usage generation);
  2. classes first seen this batch evaluate their full [N] row once
     and stay resident;
  3. the solver consumes a batch-ordered gather — the `statics=`
     operand of the warm greedy/wavefront executables.

Resync discipline (the mirror's, applied whole):

  * full recompute when the struct generation moved, the padded node
    bucket changed, or the delta would touch more than half the rows;
  * full FLUSH (keys dropped) when an expansion-relevant vocabulary
    grew — selector/preferred rows are expanded against the vocab at
    encode time, so a grown vocab silently changes what a cached row
    SHOULD contain (the key can't see it; the watermark can);
  * a PERIODIC full recompute every `resync_interval` delta syncs —
    the standing parity discipline — plus verify(), the oracle-parity
    gate the test suite and chaos seeds drive;
  * speculation_point()/rollback() double-buffer the resident arrays
    exactly like the mirror's speculation bookmark (immutable device
    arrays make holding the reference a true double buffer), and
    invalidate() serves leadership reconcile / RESHARDED.

The `solve.partials` fault point fires here: CORRUPT poisons the
resident score rows with NaN so the decode-side health check
(SolveUnhealthy) trips and the breaker/retry path falls back to a full
recompute — the parity gate's runtime wire.

All state is mutated under the scheduler-cache lock (sync() shares
encode_pending's locked section), like the mirror's counters.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..analysis import epochs, retrace
from ..ops import partials as pops
from ..ops import schema
from ..testing import faults
from ..utils import vocab as vb

_DOMAIN_LABELS = schema.DOMAIN_LABELS


def _pad_idx(idx: np.ndarray, bucket: int) -> np.ndarray:
    out = np.full(bucket, idx[0], dtype=np.int32)
    out[: idx.shape[0]] = idx
    return out


@jax.jit
def _poison_aff(store: pops.PartialsStore) -> pops.PartialsStore:
    """CORRUPT-grade fault: poison the resident raw-affinity rows with
    +inf.  The per-pod normalization divides by the feasible-set max —
    floor(100 * inf / inf) is NaN — so every feasible node's score goes
    NaN and the decode health check trips
    (models.batch_scheduler.SolveUnhealthy).  A direct NaN poison would
    be SQUASHED: normalize's `where(m > 0, ...)` reads a NaN max as
    False and silently zeroes the row — wrong scores with no trip."""
    import jax.numpy as jnp

    return store._replace(aff=jnp.full_like(store.aff, jnp.inf))


class PartialsCache:
    """One consumer's resident Filter/Score partials for a ClusterState
    (each TPUBatchScheduler owns one, next to its DeviceClusterMirror)."""

    # deltas touching more rows than this fraction fall back to a full
    # recompute (the mirror's threshold, same rationale)
    FULL_SYNC_FRACTION = 0.5
    # forced full recompute every this many delta syncs — the periodic
    # half of the resync/parity discipline
    DEFAULT_RESYNC_INTERVAL = 1024
    MIN_SLOTS = 32
    MAX_SLOTS = 1024
    # FIXED dispatch buckets: dirty rows refresh in ROW_CHUNK-sized
    # chunks and misses insert in MISS_CHUNK-sized chunks (padded by
    # repeating the first index), so each cache compiles exactly ONE
    # refresh and ONE insert executable per (cap, n, r) instead of
    # walking a delta-size bucket ladder with a ~1s XLA compile on the
    # hot path at every first-seen bucket (a bench c6 trace-overrun
    # finding).  A 3-row delta evaluating 256 padded rows costs ~cap*256
    # elementwise ops — noise next to one solve.
    ROW_CHUNK = 256
    MISS_CHUNK = 8

    def __init__(
        self,
        state: schema.ClusterState,
        mesh=None,
        resync_interval: int = DEFAULT_RESYNC_INTERVAL,
    ):
        self.state = state
        self.mesh = mesh
        self.resync_interval = max(int(resync_interval), 1)
        # graftcoh-registered device-resident buffer (docs/static_analysis.md)
        self._store: Optional[pops.PartialsStore] = None  # resident: fault=solve.partials chaos=PARTIALS_SEEDS
        self._specs: Optional[pops.ClassSpecs] = None
        self._slots: Dict[tuple, int] = {}
        self._cap = 0
        self._n = 0
        self._synced_gen = 0
        self._struct_gen = 0
        self._vocab_key: Optional[tuple] = None
        self._since_full = 0
        # epoch stamp + invalidation fence (analysis/epochs.py;
        # models/mirror.py carries the same pair and documents the
        # rollback-resurrection hazard the fence closes)
        self._epoch: Optional[epochs.EpochStamp] = None
        self._inval_gen = 0
        # counters (mirrored into scheduler_partials_* each cycle and
        # read by bench's hit-rate reporting); mutated under the cache
        # lock — sync() runs inside encode_pending's locked section
        self.hit_rows_total = 0         # [class, row] entries served warm
        self.recomputed_rows_total = 0  # node rows re-evaluated
        self.full_recomputes = 0        # full store recomputes (any cause)
        self.rollbacks = 0              # speculation rollbacks
        self.delta_syncs = 0
        self.grows = 0                  # in-place node-axis grows/shrinks
        # safety valve (the mirror's, same contract): False restores the
        # pre-elastic behavior — any node-axis change reseeds the whole
        # store, dropping every warm class row
        self.incremental_grow = True
        if mesh is None:
            self._put = jax.device_put
            self._eval = pops.eval_store_jit
            self._refresh = pops.refresh_rows_jit
            self._insert = pops.insert_slots_jit
            self._gather = pops.gather_statics_jit
            self._set_specs = pops.set_spec_rows_jit
            self._grow_cols = pops.grow_store_cols_jit
            self._shrink_cols = pops.shrink_store_cols_jit
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            axis = mesh.axis_names[0]
            row_sh = NamedSharding(mesh, P(None, axis))
            rep_sh = NamedSharding(mesh, P())
            store_sh = pops.PartialsStore(
                sfeas=row_sh, aff=row_sh, taint=row_sh
            )
            statics_sh = pops.ClassStatics(
                sfeas=row_sh, aff=row_sh, taint=row_sh
            )
            # small uploads (spec rows, index buckets) replicate so every
            # jit operand shares the mesh's device set; store outputs pin
            # to the resident layout so executable keys never drift
            # (models/mirror.py, same discipline).  Replicated-resident
            # buckets (smaller than the mesh) use the plain twins below.
            self._put = lambda x: jax.device_put(x, rep_sh)
            self._eval = jax.jit(pops.eval_store, out_shardings=store_sh)
            self._refresh = jax.jit(
                pops.refresh_rows, out_shardings=store_sh
            )
            self._insert = jax.jit(pops.insert_slots, out_shardings=store_sh)
            self._gather = jax.jit(
                pops.gather_statics, out_shardings=statics_sh
            )
            self._set_specs = pops.set_spec_rows_jit
            self._grow_cols = jax.jit(
                pops.grow_store_cols, static_argnums=(1,),
                out_shardings=store_sh,
            )
            self._shrink_cols = jax.jit(
                pops.shrink_store_cols, static_argnums=(1,),
                out_shardings=store_sh,
            )
            self._eval_rep = pops.eval_store_jit
            self._refresh_rep = pops.refresh_rows_jit
            self._insert_rep = pops.insert_slots_jit
            self._gather_rep = pops.gather_statics_jit
            self._grow_cols_rep = pops.grow_store_cols_jit
            self._shrink_cols_rep = pops.shrink_store_cols_jit
        self._resident_sharded = False

    # -- bookkeeping -------------------------------------------------------

    def stats(self) -> dict:
        return {
            "hit_rows_total": self.hit_rows_total,
            "recomputed_rows_total": self.recomputed_rows_total,
            "full_recomputes": self.full_recomputes,
            "rollbacks": self.rollbacks,
            "delta_syncs": self.delta_syncs,
            "slots": len(self._slots),
            "grows": self.grows,
        }

    def epoch(self) -> Optional[epochs.EpochStamp]:
        """The resident store's epoch stamp (None when invalidated,
        declined, or never synced) — read by the GRAFTLINT_COHERENCE
        auditor."""
        return self._epoch

    def speculation_point(self) -> tuple:
        """Bookmark the resident buffers for a speculative encode —
        device arrays are immutable, so holding the references IS the
        double buffer (models.mirror.DeviceClusterMirror
        .speculation_point, same contract: caller holds the cache
        lock)."""
        return (
            self._store, self._specs, dict(self._slots), self._cap,
            self._n, self._synced_gen, self._struct_gen, self._vocab_key,
            self._since_full, self._resident_sharded, self._epoch,
            self._inval_gen,
        )

    def rollback(self, point: tuple) -> None:
        """Restore a speculation_point() bookmark: the speculative batch
        was invalidated, so the rows refreshed/inserted for it are
        dropped whole; the next sync re-evaluates every row dirtied
        since the bookmarked generation.  Counted into
        scheduler_partials_rollbacks_total.  Refused (stays
        invalidated) when an invalidate() landed after the bookmark —
        the fence contract documented on DeviceClusterMirror.rollback."""
        (
            store, specs, slots, cap, n, synced_gen, struct_gen,
            vocab_key, since_full, resident_sharded, epoch_stamp,
            inval_gen,
        ) = point
        if inval_gen != self._inval_gen:
            epochs.note_rollback_blocked("partials")
            return
        self._store = store
        self._specs = specs
        self._slots = dict(slots)
        self._cap = cap
        self._n = n
        self._synced_gen = synced_gen
        self._struct_gen = struct_gen
        self._vocab_key = vocab_key
        self._since_full = since_full
        self._resident_sharded = resident_sharded
        self._epoch = epoch_stamp
        self.rollbacks += 1

    def invalidate(self) -> None:
        """Drop the resident buffers AND the signature map: the next
        sync performs a full recompute from the current batch.
        Leadership reconcile calls this alongside mirror.invalidate()
        (a reconciled cache's generation history no longer matches the
        resident rows), and the device-solve retry path calls it before
        re-encoding (resident state is a fault suspect)."""
        self._store = None
        self._specs = None
        self._slots = {}
        self._cap = 0
        self._n = 0
        self._synced_gen = 0
        self._struct_gen = 0
        self._vocab_key = None
        self._since_full = 0
        self._epoch = None
        self._inval_gen += 1

    def _vocab_watermark(self) -> tuple:
        """Selector/preferred rows expand Exists/NotIn/Gt/Lt against the
        CURRENT vocabularies at encode time (schema._expand_requirement)
        — a grown vocab changes what a cached row should contain without
        changing its signature, so growth flushes the cache whole.  The
        watermark is PER REFERENCED KEY (builder.expansion_watermark):
        only keys some encoded requirement actually expanded against
        count, so the label pairs every autoscaled node interns (its
        hostname, fresh zone values under unreferenced keys) do NOT
        flush warm rows — sustained node churn keeps the cache hot (the
        elastic-node-axis contract; bench c12 gates it).  Toleration
        re-expansions are self-keying (the expanded bitset bytes are
        part of the class key), so the taint vocab is not watermarked."""
        return self.state.builder.expansion_watermark()

    # -- signature keying --------------------------------------------------

    @staticmethod
    def class_key(
        pods: schema.PodBatch, rep: int, meta: schema.SnapshotMeta
    ) -> tuple:
        """Content signature of one class representative's STATIC spec —
        exactly the inputs of the partials triple (name/selector/
        tolerations/ports/preferred), with the batch-local table indices
        replaced by the builder's persistent signature-registry ids
        (SnapshotMeta.sel_stable / pref_stable) so the key is stable
        across batches.  Requests are deliberately excluded: classes
        differing only in resources share one partials row."""
        si = int(pods.sel_idx[rep])
        mt = pods.pref_idx.shape[1]
        prefs = tuple(
            (
                meta.pref_stable[int(pods.pref_idx[rep, j])]
                if int(pods.pref_idx[rep, j]) >= 0
                else -1,
                float(pods.pref_weight[rep, j]),
            )
            for j in range(mt)
        )
        return (
            bool(pods.valid[rep]),
            int(pods.name_id[rep]),
            meta.sel_stable[si] if si >= 0 else -1,
            np.ascontiguousarray(pods.tol_bits[:, rep, :]).tobytes(),
            np.ascontiguousarray(pods.tol_all[:, rep]).tobytes(),
            np.ascontiguousarray(pods.port_bits[rep]).tobytes(),
            prefs,
        )

    def _spec_row(self, snap: schema.Snapshot, rep: int) -> tuple:
        """One ClassSpecs row (host numpy leaves) for a representative
        pod, byte-copied from the batch tables."""
        pods, sel, pref = snap.pods, snap.selectors, snap.preferred
        lim = self.state.builder.limits
        t_cap, e_cap, k_cap, mt = (
            lim.max_terms, lim.max_exprs, lim.max_ids_per_expr,
            lim.max_preferred,
        )
        si = int(pods.sel_idx[rep])
        if si >= 0:
            sel_ids = np.array(sel.expr_ids[si])
            sel_op = np.array(sel.expr_op[si])
            sel_slot = np.array(sel.expr_slot[si])
            sel_tv = np.array(sel.term_valid[si])
        else:
            sel_ids = np.full((t_cap, e_cap, k_cap), -1, dtype=np.int32)
            sel_op = np.zeros((t_cap, e_cap), dtype=np.int32)
            sel_slot = np.full((t_cap, e_cap), _DOMAIN_LABELS, dtype=np.int32)
            sel_tv = np.zeros(t_cap, dtype=bool)
        pref_ids = np.full((mt, e_cap, k_cap), -1, dtype=np.int32)
        pref_op = np.zeros((mt, e_cap), dtype=np.int32)
        pref_slot = np.full((mt, e_cap), _DOMAIN_LABELS, dtype=np.int32)
        pref_valid = np.zeros(mt, dtype=bool)
        pref_weight = np.zeros(mt, dtype=np.float32)
        for j in range(mt):
            pi = int(pods.pref_idx[rep, j])
            if pi < 0:
                continue
            pref_ids[j] = pref.expr_ids[pi]
            pref_op[j] = pref.expr_op[pi]
            pref_slot[j] = pref.expr_slot[pi]
            pref_valid[j] = True
            pref_weight[j] = pods.pref_weight[rep, j]
        return (
            bool(pods.valid[rep]), int(pods.name_id[rep]), si >= 0,
            sel_ids, sel_op, sel_slot, sel_tv,
            np.array(pods.tol_bits[:, rep, :]),
            np.array(pods.tol_all[:, rep]),
            np.array(pods.port_bits[rep]),
            pref_ids, pref_op, pref_slot, pref_valid, pref_weight,
        )

    def _stack_spec_rows(self, rows: List[tuple], bucket: int) -> pops.ClassSpecs:
        """Stack host spec rows into an [Mpad]-bucketed ClassSpecs
        (padding repeats the first row — duplicate scatter of identical
        values is a no-op)."""
        pad = [rows[0]] * (bucket - len(rows))
        rows = rows + pad
        cols = list(zip(*rows))
        return pops.ClassSpecs(
            valid=np.array(cols[0], dtype=bool),
            name_id=np.array(cols[1], dtype=np.int32),
            has_sel=np.array(cols[2], dtype=bool),
            sel_ids=np.stack(cols[3]),
            sel_op=np.stack(cols[4]),
            sel_slot=np.stack(cols[5]),
            sel_tv=np.stack(cols[6]),
            tol_bits=np.stack(cols[7], axis=1),
            tol_all=np.stack(cols[8], axis=1),
            port_bits=np.stack(cols[9]),
            pref_ids=np.stack(cols[10]),
            pref_op=np.stack(cols[11]),
            pref_slot=np.stack(cols[12]),
            pref_valid=np.stack(cols[13]),
            pref_weight=np.stack(cols[14]),
        )

    def _empty_specs(self, cap: int) -> pops.ClassSpecs:
        lim = self.state.builder.limits
        t_cap, e_cap, k_cap, mt = (
            lim.max_terms, lim.max_exprs, lim.max_ids_per_expr,
            lim.max_preferred,
        )
        return pops.ClassSpecs(
            valid=np.zeros(cap, dtype=bool),
            name_id=np.full(cap, -1, dtype=np.int32),
            has_sel=np.zeros(cap, dtype=bool),
            sel_ids=np.full((cap, t_cap, e_cap, k_cap), -1, dtype=np.int32),
            sel_op=np.zeros((cap, t_cap, e_cap), dtype=np.int32),
            sel_slot=np.full(
                (cap, t_cap, e_cap), _DOMAIN_LABELS, dtype=np.int32
            ),
            sel_tv=np.zeros((cap, t_cap), dtype=bool),
            tol_bits=np.zeros(
                (3, cap, lim.taint_words), dtype=np.uint32
            ),
            tol_all=np.zeros((3, cap), dtype=bool),
            port_bits=np.zeros((cap, lim.port_words), dtype=np.uint32),
            pref_ids=np.full((cap, mt, e_cap, k_cap), -1, dtype=np.int32),
            pref_op=np.zeros((cap, mt, e_cap), dtype=np.int32),
            pref_slot=np.full(
                (cap, mt, e_cap), _DOMAIN_LABELS, dtype=np.int32
            ),
            pref_valid=np.zeros((cap, mt), dtype=bool),
            pref_weight=np.zeros((cap, mt), dtype=np.float32),
        )

    # -- the sync protocol -------------------------------------------------

    def _kernels(self):
        """(eval, refresh, insert, gather): the pinned-sharding twins
        when the resident layout is node-axis sharded, the plain ones
        otherwise (single chip, or replicated small-bucket residents —
        the same batches the solver runs single-chip)."""
        if self.mesh is not None and not self._resident_sharded:
            return (
                self._eval_rep, self._refresh_rep, self._insert_rep,
                self._gather_rep,
            )
        return self._eval, self._refresh, self._insert, self._gather

    def sync(
        self,
        cluster,
        snap: schema.Snapshot,
        meta: schema.SnapshotMeta,
        cluster_epoch: Optional[epochs.EpochStamp] = None,
    ) -> Optional[pops.ClassStatics]:
        """Warm statics for this batch, or None when the cache declines
        (capacity overflow past MAX_SLOTS with more classes than fit).
        `cluster` is the mirror's device-resident ClusterTensors for the
        state's CURRENT generation — the exact tensors the solve
        consumes, so warm rows are evaluated against what the cold path
        would see.  Caller holds the cache lock (mirror.sync contract);
        `snap` is still host-resident (pre-transfer).  `cluster_epoch`
        is the mirror's epoch stamp for `cluster` — the resident store's
        stamp inherits its buffer lineage so the GRAFTLINT_COHERENCE
        auditor can tie the rows to the exact mirror buffer they were
        evaluated against."""
        state = self.state
        class_rep = np.asarray(snap.pods.class_rep)
        c_dim = class_rep.shape[0]
        n_real = int((class_rep >= 0).sum())
        act = faults.fire("solve.partials", classes=n_real)
        keys = [
            self.class_key(snap.pods, int(class_rep[c]), meta)
            for c in range(n_real)
        ]
        n = int(cluster.allocatable.shape[0])
        vkey = self._vocab_watermark()
        if self.mesh is not None:
            sharded = n % int(self.mesh.devices.size) == 0
        else:
            sharded = False

        stale = (
            self._store is None
            or self._struct_gen < state.struct_generation
            or self._vocab_key != vkey
            or self._resident_sharded != sharded
            # the incremental_grow valve off: any node-axis change
            # reseeds the store (the pre-elastic behavior, kept as the
            # oracle/safety path)
            or (self._n != n and not self.incremental_grow)
        )
        # distinct first-seen keys (two classes differing only in
        # requests share one slot — requests are not in the key)
        misses = list(
            dict.fromkeys(k for k in keys if k not in self._slots)
        )
        needed = len(self._slots) + len(misses)
        if needed > self._cap:
            if needed > self.MAX_SLOTS:
                return None  # more live classes than the cache may hold
            stale = True  # reallocation: reseed from this batch
        if not stale and self._since_full >= self.resync_interval:
            stale = True  # periodic full recompute (parity discipline)

        self._resident_sharded = sharded
        ev, rf, ins, ga = self._kernels()
        if stale:
            self._full_reset(cluster, snap, keys, n, vkey, ev)
        else:
            static_idx, usage_idx = state.dirty_rows(self._synced_gen, n)
            dirty = np.union1d(static_idx, usage_idx).astype(np.int32)
            if dirty.shape[0] > self.FULL_SYNC_FRACTION * n:
                self._full_reset(cluster, snap, keys, n, vkey, ev)
            else:
                if self._n != n:
                    # elastic node axis: the padded bucket moved while
                    # struct/vocab identity held — resize the resident
                    # [G, N] columns in place, keeping every cached
                    # class row warm across the crossing
                    self._resize_store(cluster, n, rf)
                miss_set = set(misses)
                hits = sum(1 for k in keys if k not in miss_set)
                if misses:
                    reps_by_key = {}
                    for c in range(n_real):
                        reps_by_key.setdefault(keys[c], int(class_rep[c]))
                    miss_rows, miss_idx = [], []
                    for k in misses:
                        slot = len(self._slots)
                        self._slots[k] = slot
                        miss_rows.append(self._spec_row(snap, reps_by_key[k]))
                        miss_idx.append(slot)
                    r = int(cluster.allocatable.shape[1])
                    chunk = self.MISS_CHUNK
                    for off in range(0, len(miss_idx), chunk):
                        seg_rows = miss_rows[off:off + chunk]
                        seg_idx = np.asarray(
                            miss_idx[off:off + chunk], np.int32
                        )
                        idx = self._put(_pad_idx(seg_idx, chunk))
                        rows = jax.tree.map(
                            self._put,
                            self._stack_spec_rows(seg_rows, chunk),
                        )
                        self._specs = self._set_specs(self._specs, rows, idx)
                        self._store = ins(
                            self._store, self._specs, cluster, idx
                        )
                    retrace.note(
                        "partials-insert", ins,
                        lambda: ("partials-insert", self._cap, n, r, chunk,
                                 self._resident_sharded),
                    )
                    self.recomputed_rows_total += len(miss_idx) * n
                if dirty.shape[0]:
                    r = int(cluster.allocatable.shape[1])
                    chunk = min(self.ROW_CHUNK, n)
                    for off in range(0, dirty.shape[0], chunk):
                        idx = self._put(
                            _pad_idx(dirty[off:off + chunk], chunk)
                        )
                        self._store = rf(
                            self._store, self._specs, cluster, idx
                        )
                    retrace.note(
                        "partials-refresh", rf,
                        lambda: ("partials-refresh", self._cap, n, r, chunk,
                                 self._resident_sharded),
                    )
                    self.recomputed_rows_total += int(dirty.shape[0])
                self.hit_rows_total += max(hits, 0) * (n - int(dirty.shape[0]))
                self.delta_syncs += 1
                self._since_full += 1
                self._synced_gen = state.generation
        # stamp AFTER both paths: the store now matches the cache's
        # current generations, and its lineage follows the mirror buffer
        # the rows were evaluated against (a CORRUPT fault below poisons
        # CONTENT, not epochs — the parity gate / heal wire owns that)
        self._epoch = epochs.EpochStamp(
            "partials", self._struct_gen, self._vocab_key,
            self._synced_gen,
            cluster_epoch.buffer_id if cluster_epoch is not None else 0,
        )

        if act == faults.CORRUPT:
            # poison the RESIDENT partials: the warm solve's scores go
            # NaN, the decode health check trips, and the retry path
            # invalidates this cache → full recompute (or the breaker's
            # host fallback) — chaos seeds 700-704 assert the healing
            self._store = _poison_aff(self._store)

        # batch-ordered slot gather ([C] — padded classes alias class
        # 0's slot, the clipped-representative convention)
        slot_arr = np.empty(c_dim, dtype=np.int32)
        for c in range(c_dim):
            slot_arr[c] = self._slots[keys[c if c < n_real else 0]]
        statics = ga(self._store, self._put(slot_arr))
        retrace.note(
            "partials-gather", ga,
            lambda: ("partials-gather", self._cap, n, c_dim,
                     self._resident_sharded),
        )
        return statics

    def _grow_kernels(self):
        """(grow_cols, shrink_cols): the pinned-sharding twins when the
        resident layout is node-axis sharded, the plain ones otherwise
        (the _kernels() convention)."""
        if self.mesh is not None and not self._resident_sharded:
            return self._grow_cols_rep, self._shrink_cols_rep
        return self._grow_cols, self._shrink_cols

    def _resize_store(self, cluster, n: int, rf) -> None:
        """In-place node-axis resize of the resident store (the elastic
        node axis): grow pads zero columns on device and immediately
        re-evaluates the new column range against the grown cluster —
        every cached class row stays warm across the pad-bucket
        crossing, at O(new columns) device work and O(new rows) index
        transfer; shrink slices (live rows are always below the new
        bucket by the watermark invariant)."""
        grow_c, shrink_c = self._grow_kernels()
        old_n = self._n
        if n > old_n:
            self._store = grow_c(self._store, n - old_n)
            gidx = np.arange(old_n, n, dtype=np.int32)
            chunk = vb.pad_dim(int(gidx.shape[0]), 1)
            idx = self._put(_pad_idx(gidx, chunk))
            self._store = rf(self._store, self._specs, cluster, idx)
            self.recomputed_rows_total += int(gidx.shape[0])
            retrace.note(
                "partials-grow", grow_c,
                lambda: ("partials-grow", self._cap, old_n, n,
                         self._resident_sharded),
            )
        else:
            self._store = shrink_c(self._store, n)
            retrace.note(
                "partials-shrink", shrink_c,
                lambda: ("partials-shrink", self._cap, old_n, n,
                         self._resident_sharded),
            )
        self.grows += 1
        self._n = n

    def _full_reset(self, cluster, snap, keys, n, vkey, ev) -> None:
        """Reseed the cache from this batch's classes and recompute the
        whole store in one dispatch (first sync, struct/shape/vocab
        invalidation, over-fraction delta, periodic resync, growth)."""
        state = self.state
        class_rep = np.asarray(snap.pods.class_rep)
        self._slots = {}
        rows: List[tuple] = []
        for c, k in enumerate(keys):
            if k in self._slots:
                continue
            self._slots[k] = len(rows)
            rows.append(self._spec_row(snap, int(class_rep[c])))
        cap = min(
            max(vb.pad_dim(max(len(rows), 1), self.MIN_SLOTS), self._cap),
            self.MAX_SLOTS,
        )
        specs = self._empty_specs(cap)
        if rows:
            stacked = self._stack_spec_rows(rows, len(rows))
            specs = pops.ClassSpecs(
                valid=_scatter0(specs.valid, stacked.valid),
                name_id=_scatter0(specs.name_id, stacked.name_id),
                has_sel=_scatter0(specs.has_sel, stacked.has_sel),
                sel_ids=_scatter0(specs.sel_ids, stacked.sel_ids),
                sel_op=_scatter0(specs.sel_op, stacked.sel_op),
                sel_slot=_scatter0(specs.sel_slot, stacked.sel_slot),
                sel_tv=_scatter0(specs.sel_tv, stacked.sel_tv),
                tol_bits=_scatter1(specs.tol_bits, stacked.tol_bits),
                tol_all=_scatter1(specs.tol_all, stacked.tol_all),
                port_bits=_scatter0(specs.port_bits, stacked.port_bits),
                pref_ids=_scatter0(specs.pref_ids, stacked.pref_ids),
                pref_op=_scatter0(specs.pref_op, stacked.pref_op),
                pref_slot=_scatter0(specs.pref_slot, stacked.pref_slot),
                pref_valid=_scatter0(specs.pref_valid, stacked.pref_valid),
                pref_weight=_scatter0(
                    specs.pref_weight, stacked.pref_weight
                ),
            )
        self._specs = jax.tree.map(self._put, specs)
        self._store = ev(cluster, self._specs)
        r = int(cluster.allocatable.shape[1])
        retrace.note(
            "partials-eval", ev,
            lambda: ("partials-eval", cap, n, r, self._resident_sharded),
        )
        self._cap = cap
        self._n = n
        self._synced_gen = state.generation
        self._struct_gen = state.struct_generation
        self._vocab_key = vkey
        self._since_full = 0
        self.full_recomputes += 1
        self.recomputed_rows_total += len(rows) * n

    # -- the oracle-parity gate --------------------------------------------

    def verify(self, cluster, snap: schema.Snapshot) -> bool:
        """Recompute every cached slot's row from scratch and compare to
        the resident store — the parity gate the test suite and chaos
        triage drive (not on the hot path).  A mismatch invalidates the
        cache (next sync performs a full recompute) and returns False."""
        if self._store is None or self._specs is None:
            return True
        ev = self._kernels()[0]
        want = jax.device_get(ev(cluster, self._specs))
        got = jax.device_get(self._store)
        for f in pops.PartialsStore._fields:
            w, g = getattr(want, f), getattr(got, f)
            ok = (
                np.array_equal(w, g)
                if f == "sfeas"
                else np.array_equal(w, g, equal_nan=True) and not np.isnan(
                    np.asarray(g)
                ).any()
            )
            if not ok:
                logging.getLogger(__name__).warning(
                    "partials parity gate tripped on %s: forcing full "
                    "recompute", f,
                )
                self.invalidate()
                return False
        return True


def _scatter0(base: np.ndarray, rows: np.ndarray) -> np.ndarray:
    out = np.array(base)
    out[: rows.shape[0]] = rows
    return out


def _scatter1(base: np.ndarray, rows: np.ndarray) -> np.ndarray:
    out = np.array(base)
    out[:, : rows.shape[1]] = rows
    return out
