"""Flagship end-to-end models built from the ops kernels."""
