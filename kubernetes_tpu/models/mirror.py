"""Device-resident cluster mirror — delta uploads instead of full
snapshots.

The cluster half of a Snapshot (allocatable/requested/label-bits/... —
~98% of the bytes at 50k nodes) changes by a handful of rows per
scheduling step: assumes touch `requested` on the placed nodes, node
add/update/remove touches one row.  Shipping the whole thing to the
device every encode costs ~1 s at 64k padded nodes over a tunneled
link and dominates end-to-end step latency (the round-3 north-star
regression: the solve itself is ~0.1 s).

This mirror keeps the last-uploaded cluster tensors resident on device
and applies ClusterState's generation-tracked row deltas with jitted
scatter-sets — the device-side completion of the reference's
incremental UpdateSnapshot design (internal/cache/cache.go:185-260:
walk nodes by generation, stop at the first unchanged one).  Full
re-upload happens only when the backing arrays were reallocated
(growth past the padded bucket, resource-axis widening — ClusterState
.struct_generation) or the padded shape changed.

Row updates are bucketed to powers of two and padded by repeating the
first dirty row (duplicate scatter-set of identical values is a
no-op), so the jit cache stays small and stable.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import numpy as np

from ..ops import schema
from ..utils import vocab as vb

# Leaves of ClusterTensors grouped by which mutation family dirties
# them (ClusterState._static_gen / _usage_gen).  taint_bits is handled
# separately: its node axis is axis 1.
_STATIC_LEAVES = (
    "allocatable", "node_valid", "name_id", "label_bits", "topo_ids",
    "image_bits",
)
_USAGE_LEAVES = ("requested", "nonzero_requested", "port_bits")


@jax.jit
def _set_rows(arr, idx, vals):
    return arr.at[idx].set(vals)


@jax.jit
def _set_rows_ax1(arr, idx, vals):
    return arr.at[:, idx].set(vals)


def _pad_idx(idx: np.ndarray, bucket: int) -> np.ndarray:
    out = np.full(bucket, idx[0], dtype=np.int32)
    out[: idx.shape[0]] = idx
    return out


class DeviceClusterMirror:
    """One consumer's device copy of a ClusterState's cluster tensors.

    Each TPUBatchScheduler owns its own mirror; several schedulers
    (profiles) sharing one ClusterState sync independently through the
    state's generation counters — the same protocol the reference uses
    for its per-snapshot generation watermark."""

    # Deltas touching more rows than this fraction of the cluster fall
    # back to a full upload: the scatter machinery stops paying for
    # itself once most rows move (e.g. right after a bulk node load).
    FULL_SYNC_FRACTION = 0.5

    def __init__(self, state: schema.ClusterState):
        self.state = state
        self._dev: Optional[schema.ClusterTensors] = None
        self._synced_gen = 0
        self._struct_gen = 0
        self._shape: Optional[Tuple] = None

    def sync(self) -> schema.ClusterTensors:
        """Return device-resident cluster tensors matching the state's
        current contents.  Caller must hold the cache lock (the host
        arrays are read here)."""
        state = self.state
        host = state.tensors()
        shape = tuple(np.shape(leaf) for leaf in host)
        n = host.allocatable.shape[0]
        stale_struct = (
            self._dev is None
            or self._struct_gen < state.struct_generation
            or self._shape != shape
        )
        if not stale_struct and self._synced_gen == state.generation:
            return self._dev
        if stale_struct:
            dev = self._full_upload(host)
        else:
            static_idx, usage_idx = state.dirty_rows(self._synced_gen, n)
            if (
                static_idx.shape[0] + usage_idx.shape[0]
                > self.FULL_SYNC_FRACTION * n
            ):
                dev = self._full_upload(host)
            else:
                dev = self._apply_deltas(host, static_idx, usage_idx)
        self._dev = dev
        self._synced_gen = state.generation
        self._struct_gen = state.struct_generation
        self._shape = shape
        return dev

    def _full_upload(self, host: schema.ClusterTensors) -> schema.ClusterTensors:
        # host-copy before device_put: on the CPU backend device_put can
        # zero-copy a numpy view, which would alias live cache state
        # (see TPUBatchScheduler.encode_pending's aliasing note)
        return jax.device_put(jax.tree.map(np.array, host))

    def _apply_deltas(
        self,
        host: schema.ClusterTensors,
        static_idx: np.ndarray,
        usage_idx: np.ndarray,
    ) -> schema.ClusterTensors:
        dev = self._dev
        updates = {}
        if static_idx.shape[0]:
            bucket = vb.pad_dim(static_idx.shape[0], 1)
            pidx = _pad_idx(static_idx, bucket)
            idx_dev = jax.device_put(pidx)
            for leaf in _STATIC_LEAVES:
                vals = jax.device_put(np.asarray(getattr(host, leaf))[pidx])
                updates[leaf] = _set_rows(getattr(dev, leaf), idx_dev, vals)
            tvals = jax.device_put(np.asarray(host.taint_bits)[:, pidx])
            updates["taint_bits"] = _set_rows_ax1(dev.taint_bits, idx_dev, tvals)
        if usage_idx.shape[0]:
            bucket = vb.pad_dim(usage_idx.shape[0], 1)
            pidx = _pad_idx(usage_idx, bucket)
            idx_dev = jax.device_put(pidx)
            base = dev._replace(**updates) if updates else dev
            for leaf in _USAGE_LEAVES:
                vals = jax.device_put(np.asarray(getattr(host, leaf))[pidx])
                updates[leaf] = _set_rows(getattr(base, leaf), idx_dev, vals)
        return dev._replace(**updates) if updates else dev
