"""Device-resident cluster mirror — delta uploads instead of full
snapshots.

The cluster half of a Snapshot (allocatable/requested/label-bits/... —
~98% of the bytes at 50k nodes) changes by a handful of rows per
scheduling step: assumes touch `requested` on the placed nodes, node
add/update/remove touches one row.  Shipping the whole thing to the
device every encode costs ~1 s at 64k padded nodes over a tunneled
link and dominates end-to-end step latency (the round-3 north-star
regression: the solve itself is ~0.1 s).

This mirror keeps the last-uploaded cluster tensors resident on device
and applies ClusterState's generation-tracked row deltas with jitted
scatter-sets — the device-side completion of the reference's
incremental UpdateSnapshot design (internal/cache/cache.go:185-260:
walk nodes by generation, stop at the first unchanged one).  The node
axis is ELASTIC: a pad-bucket crossing (autoscaler growth or a
post-dwell shrink) resizes the resident arrays IN PLACE — a device-side
pad/concat (or slice) carries every old row over and the new rows'
content rides the ordinary delta scatter, so a bucket crossing costs
O(new rows) host→device, not a full re-upload.  Full re-upload happens
only for genuine identity changes (resource-axis widening —
ClusterState.struct_generation — or invalidate()), for over-fraction
deltas, and as the safety path whenever the incremental resize
declines (sharded↔replicated layout flips, the incremental_grow valve,
injected mirror.grow faults).

Under a device mesh (mesh not None) the resident tensors carry a
NamedSharding over the node axis — the same layout the sharded solvers'
shard_map specs expect (parallel.sharded.CLUSTER_SPECS), so a mesh-mode
solve consumes the mirror without any per-batch resharding.  Row deltas
scatter into the owning shard: the bucketed index/value uploads are
replicated (tiny) and the jitted scatter — pinned to the resident
sharding via out_shardings so the executable key never drifts — lets
GSPMD route each row to its shard.  Struct-generation changes trigger a
full RESHARDED re-upload, exactly like the single-device case.

Row updates are bucketed to powers of two and padded by repeating the
first dirty row (duplicate scatter-set of identical values is a
no-op), so the jit cache stays small and stable.

`resync_total` / `delta_rows_total` / `delta_syncs` count full uploads
and real (unbucketed) scattered rows — the scheduler mirrors them into
`scheduler_mirror_resync_total` / `scheduler_mirror_delta_rows`, and
bench's c7 gates on steady-state transfer being O(changed rows).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Optional, Tuple

import jax
import numpy as np

from ..analysis import epochs, retrace
from ..ops import schema
from ..testing import faults
from ..utils import vocab as vb

# Leaves of ClusterTensors grouped by which mutation family dirties
# them (ClusterState._static_gen / _usage_gen).  taint_bits is handled
# separately: its node axis is axis 1.
_STATIC_LEAVES = (
    "allocatable", "node_valid", "name_id", "label_bits", "topo_ids",
    "image_bits", "slice_id", "torus_coords", "slice_dims", "slice_pos",
)
_USAGE_LEAVES = ("requested", "nonzero_requested", "port_bits")

# Pad-row fill per leaf for the incremental resident grow: MUST match
# ClusterState._alloc's defaults — rows beyond the watermark the host
# never wrote read these values, and the grow carries them on device
# without any host transfer (leaves absent here fill with 0).
_GROW_FILLS = {
    "name_id": -1, "topo_ids": -1, "slice_id": -1, "torus_coords": -1,
    "slice_pos": -1,
}


@jax.jit
def _set_rows(arr, idx, vals):
    return arr.at[idx].set(vals)


@jax.jit
def _set_rows_ax1(arr, idx, vals):
    return arr.at[:, idx].set(vals)


# Elastic node-axis kernels: grow pads default-valued rows onto the
# resident arrays ON DEVICE (one concat per leaf, zero host transfer —
# the O(new rows) content follows through the ordinary delta scatter),
# shrink slices them.  dn / n / fill are static: one executable per
# (leaf shape, transition), reused across repeat crossings.
@partial(jax.jit, static_argnums=(1, 2))
def _grow_rows(arr, dn, fill):
    import jax.numpy as jnp

    pad = jnp.full((dn,) + arr.shape[1:], fill, arr.dtype)
    return jnp.concatenate([arr, pad], axis=0)


@partial(jax.jit, static_argnums=(1, 2))
def _grow_rows_ax1(arr, dn, fill):
    import jax.numpy as jnp

    pad = jnp.full(arr.shape[:1] + (dn,) + arr.shape[2:], fill, arr.dtype)
    return jnp.concatenate([arr, pad], axis=1)


@partial(jax.jit, static_argnums=(1,))
def _shrink_rows(arr, n):
    return arr[:n]


@partial(jax.jit, static_argnums=(1,))
def _shrink_rows_ax1(arr, n):
    return arr[:, :n]


def _pad_idx(idx: np.ndarray, bucket: int) -> np.ndarray:
    out = np.full(bucket, idx[0], dtype=np.int32)
    out[: idx.shape[0]] = idx
    return out


class DeviceClusterMirror:
    """One consumer's device copy of a ClusterState's cluster tensors.

    Each TPUBatchScheduler owns its own mirror; several schedulers
    (profiles) sharing one ClusterState sync independently through the
    state's generation counters — the same protocol the reference uses
    for its per-snapshot generation watermark."""

    # Deltas touching more rows than this fraction of the cluster fall
    # back to a full upload: the scatter machinery stops paying for
    # itself once most rows move (e.g. right after a bulk node load).
    FULL_SYNC_FRACTION = 0.5

    def __init__(self, state: schema.ClusterState, mesh=None):
        self.state = state
        self.mesh = mesh
        # graftcoh-registered device-resident buffer (docs/static_analysis.md)
        self._dev: Optional[schema.ClusterTensors] = None  # resident: fault=mirror.grow chaos=NODE_CHURN_SEEDS oracle=full-resync
        self._synced_gen = 0
        self._struct_gen = 0
        self._shape: Optional[Tuple] = None
        # epoch stamp of the resident buffer (analysis/epochs.py): the
        # GRAFTLINT_COHERENCE auditor compares it against the state's
        # CURRENT generations at consume time.  buffer id is the
        # lineage token: minted per full upload, carried by delta
        # scatters and in-place grows, restored by rollback.
        self._epoch: Optional[epochs.EpochStamp] = None
        self._buffer_id = 0
        # invalidation fence: a rollback() whose bookmark predates a
        # later invalidate() must NOT resurrect the dropped buffer
        # (leadership reconcile / the finalize_pending heal wire
        # invalidate deliberately; a mis-speculation rollback racing
        # them would restore exactly the state they dropped — a
        # graftcoh true positive, regression-pinned in
        # tests/test_coherence.py)
        self._inval_gen = 0
        # transfer accounting (read by the scheduler's metric mirror and
        # bench c7's O(changed-rows) gate); mutated under the cache lock
        # — sync() is called inside encode_pending's locked section
        self.resync_total = 0      # full uploads (first sync included)
        self.delta_rows_total = 0  # real dirty rows scattered
        self.delta_syncs = 0       # syncs served by the delta path
        # elastic node axis (docs/scheduler_loop.md): pad-bucket
        # crossings absorbed IN PLACE — a device-side pad/concat (grow)
        # or slice (shrink) carries the old resident rows over, and the
        # new rows' content rides the ordinary delta scatter.  Mirrored
        # into scheduler_mirror_grow_total / scheduler_mirror_grow_rows.
        self.grow_syncs = 0        # in-place resident grows/shrinks
        self.grow_rows_total = 0   # axis rows added without a re-upload
        # safety valve: False restores the pre-elastic behavior — every
        # shape change performs the full (RESHARDED under a mesh)
        # re-upload; the parity oracle tests and bench c12 drive it
        self.incremental_grow = True
        # whether the resident copy is node-axis sharded (False when no
        # mesh, or when the padded bucket doesn't split across it — the
        # same batches TPUBatchScheduler solves single-chip)
        self._resident_sharded = False
        if mesh is None:
            self._shardings = None
            self._set = _set_rows
            self._set_ax1 = _set_rows_ax1
            self._grow = _grow_rows
            self._grow_ax1 = _grow_rows_ax1
            self._shrink = _shrink_rows
            self._shrink_ax1 = _shrink_rows_ax1
            self._put_small = jax.device_put
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            axis = mesh.axis_names[0]
            row_sh = NamedSharding(mesh, P(axis))          # node axis = dim 0
            ax1_sh = NamedSharding(mesh, P(None, axis))    # taint_bits
            rep_sh = NamedSharding(mesh, P())
            self._shardings = schema.ClusterTensors(
                **{
                    f: (ax1_sh if f == "taint_bits" else row_sh)
                    for f in schema.ClusterTensors._fields
                }
            )
            # replicated layout for buckets the mesh can't split (the
            # single-chip fallback batches): still mesh-committed so
            # every consumer sees one device set
            self._rep_shardings = schema.ClusterTensors(
                **{f: rep_sh for f in schema.ClusterTensors._fields}
            )
            # out_shardings pin the scatter results to the resident
            # layout: without them GSPMD may pick a different output
            # sharding, and a sharding flip is a fresh executable key on
            # the NEXT delta — a steady-state recompile
            self._set = jax.jit(
                lambda a, i, v: a.at[i].set(v), out_shardings=row_sh
            )
            self._set_ax1 = jax.jit(
                lambda a, i, v: a.at[:, i].set(v), out_shardings=ax1_sh
            )
            # sharded twins of the elastic-axis kernels: the grown /
            # shrunk resident keeps the NamedSharding node-axis layout
            # (out_shardings pin it — GSPMD re-pads each shard in place,
            # no host round-trip, and the executable key never drifts)
            import jax.numpy as jnp

            self._grow = jax.jit(
                lambda a, dn, fill: jnp.concatenate(
                    [a, jnp.full((dn,) + a.shape[1:], fill, a.dtype)], axis=0
                ),
                static_argnums=(1, 2), out_shardings=row_sh,
            )
            self._grow_ax1 = jax.jit(
                lambda a, dn, fill: jnp.concatenate(
                    [a, jnp.full(a.shape[:1] + (dn,) + a.shape[2:], fill,
                                 a.dtype)],
                    axis=1,
                ),
                static_argnums=(1, 2), out_shardings=ax1_sh,
            )
            self._shrink = jax.jit(
                lambda a, n: a[:n], static_argnums=(1,), out_shardings=row_sh
            )
            self._shrink_ax1 = jax.jit(
                lambda a, n: a[:, :n], static_argnums=(1,),
                out_shardings=ax1_sh,
            )
            # index/value uploads replicate over the mesh: they are a
            # few KB, and replication keeps every jit operand on the
            # same device set (mixing single-device-committed arrays
            # with mesh-committed ones is a placement error)
            self._put_small = lambda x: jax.device_put(x, rep_sh)

    def sync(self) -> schema.ClusterTensors:
        """Return device-resident cluster tensors matching the state's
        current contents.  Caller must hold the cache lock (the host
        arrays are read here)."""
        state = self.state
        host = state.tensors()
        shape = tuple(np.shape(leaf) for leaf in host)
        n = host.allocatable.shape[0]
        stale_struct = (
            self._dev is None
            or self._struct_gen < state.struct_generation
        )
        shape_moved = not stale_struct and self._shape != shape
        if (
            not stale_struct
            and not shape_moved
            and self._synced_gen == state.generation
        ):
            return self._dev
        if stale_struct:
            dev = self._full_upload(host)
        else:
            static_idx, usage_idx = state.dirty_rows(self._synced_gen, n)
            if (
                static_idx.shape[0] + usage_idx.shape[0]
                > self.FULL_SYNC_FRACTION * n
            ):
                dev = self._full_upload(host)
            elif shape_moved:
                # elastic node axis: the padded bucket moved while row
                # identity held (growth is no longer a struct event) —
                # resize the resident arrays in place and let the delta
                # scatter carry the changed rows' content: O(new rows)
                # host→device, not a full re-upload
                resized = self._resize_resident(shape)
                if resized is None:
                    dev = self._full_upload(host)  # the safety path
                else:
                    self._dev = resized
                    dev = self._apply_deltas(host, static_idx, usage_idx)
            else:
                dev = self._apply_deltas(host, static_idx, usage_idx)
        self._dev = dev
        self._synced_gen = state.generation
        self._struct_gen = state.struct_generation
        self._shape = shape
        self._epoch = epochs.EpochStamp(
            "mirror", self._struct_gen, None, self._synced_gen,
            self._buffer_id,
        )
        return dev

    def _resize_resident(self, shape) -> Optional[schema.ClusterTensors]:
        """Grow (device-side pad) or shrink (device-side slice) the
        resident tensors to the new padded bucket, preserving every
        carried row — one on-device copy per leaf, zero host transfer.
        Returns None to decline (layout flip under a mesh, a non-node
        axis moved, the safety valve, or an injected mirror.grow
        fault), in which case the caller takes the full (RESHARDED)
        re-upload safety path."""
        old_n = self._shape[0][0]
        new_n = shape[0][0]
        if not self.incremental_grow or new_n == old_n:
            return None
        # only the node axis may differ: every other dim change is an
        # identity change the struct generation should have declared
        for f, old_s, new_s in zip(
            schema.ClusterTensors._fields, self._shape, shape
        ):
            ax = 1 if f == "taint_bits" else 0
            if (
                old_s[:ax] + old_s[ax + 1:] != new_s[:ax] + new_s[ax + 1:]
                or old_s[ax] != old_n or new_s[ax] != new_n
            ):
                return None
        if self._shardings is not None:
            sharded = new_n % self.mesh.devices.size == 0
            if sharded != self._resident_sharded:
                return None  # layout flip: full RESHARDED re-upload
        try:
            act = faults.fire("mirror.grow", old_n=old_n, new_n=new_n)
        except Exception:  # noqa: BLE001 — injected grow fault: contained
            logging.getLogger(__name__).warning(
                "mirror.grow fault injected; falling back to full resync"
            )
            return None
        grow, grow1, shrink, shrink1 = (
            self._grow, self._grow_ax1, self._shrink, self._shrink_ax1,
        )
        if self._shardings is not None and not self._resident_sharded:
            # replicated small-bucket resident: the pinned-sharding
            # kernels don't apply (models/mirror._apply_deltas, same)
            grow, grow1 = _grow_rows, _grow_rows_ax1
            shrink, shrink1 = _shrink_rows, _shrink_rows_ax1
        updates = {}
        dn = new_n - old_n
        for f in schema.ClusterTensors._fields:
            leaf = getattr(self._dev, f)
            if f == "taint_bits":
                updates[f] = (
                    grow1(leaf, dn, _GROW_FILLS.get(f, 0))
                    if dn > 0 else shrink1(leaf, new_n)
                )
            else:
                updates[f] = (
                    grow(leaf, dn, _GROW_FILLS.get(f, 0))
                    if dn > 0 else shrink(leaf, new_n)
                )
        self.grow_syncs += 1
        if dn > 0:
            self.grow_rows_total += dn
        kernel = grow if dn > 0 else shrink
        retrace.note(
            "mirror-grow", kernel,
            lambda: ("mirror-grow", old_n, new_n, self._resident_sharded),
        )
        dev = schema.ClusterTensors(**updates)
        if act == faults.CORRUPT:
            # poison the carried rows so the solve's fit scores go
            # (inf - req) / inf = NaN: the decode health check trips and
            # the retry's mirror invalidation heals via full resync —
            # the elastic axis's parity-gate wire (chaos seeds 800-804)
            import jax.numpy as jnp

            dev = dev._replace(
                allocatable=jnp.full_like(dev.allocatable, jnp.inf)
            )
        return dev

    def stats(self) -> dict:
        return {
            "resync_total": self.resync_total,
            "delta_rows_total": self.delta_rows_total,
            "delta_syncs": self.delta_syncs,
            "grow_syncs": self.grow_syncs,
            "grow_rows_total": self.grow_rows_total,
        }

    def epoch(self) -> Optional[epochs.EpochStamp]:
        """The resident buffer's epoch stamp (None when invalidated or
        never synced) — read by the GRAFTLINT_COHERENCE auditor and by
        PartialsCache.sync's lineage stamping."""
        return self._epoch

    def speculation_point(self) -> tuple:
        """Bookmark the resident buffer for a SPECULATIVE encode: the
        current device tensors + generations.  Device arrays are
        immutable, so holding the reference IS the double buffer — a
        later sync() scatters into fresh arrays while any in-flight
        solve keeps reading the bookmarked ones.  Caller holds the
        cache lock (same contract as sync())."""
        return (
            self._dev, self._synced_gen, self._struct_gen, self._shape,
            self._resident_sharded, self._epoch, self._buffer_id,
            self._inval_gen,
        )

    def rollback(self, point: tuple) -> None:
        """Restore the resident buffer to a speculation_point() bookmark
        — the speculative batch was invalidated (the wave it solved over
        failed or was fenced), so the deltas synced for it are dropped
        whole instead of layering the forget-restore scatters on top.
        Always safe: ClusterState.dirty_rows(synced_gen) covers EVERY
        row dirtied since the bookmarked generation, so the next sync()
        re-scatters anything the dropped buffer carried (or performs a
        full upload when the struct generation moved past the
        bookmark).  Caller holds the cache lock.

        EXCEPT after an intervening invalidate(): a bookmark taken
        before a leadership reconcile or the finalize_pending heal wire
        dropped the resident must not resurrect the dropped buffer —
        the invalidation fence keeps the mirror invalidated and the
        next sync() performs the full re-upload instead."""
        (
            dev, synced_gen, struct_gen, shape, resident_sharded,
            epoch_stamp, buffer_id, inval_gen,
        ) = point
        if inval_gen != self._inval_gen:
            epochs.note_rollback_blocked("mirror")
            return
        self._dev = dev
        self._synced_gen = synced_gen
        self._struct_gen = struct_gen
        self._shape = shape
        self._resident_sharded = resident_sharded
        self._epoch = epoch_stamp
        self._buffer_id = buffer_id

    def invalidate(self) -> None:
        """Drop the resident copy so the next sync() performs a full
        (RESHARDED, under a mesh) re-upload.  Leadership reconciliation
        calls this on takeover/restart: the delta protocol assumes the
        resident tensors match some past generation of THIS state's
        history, which a rebuilt or reconciled cache no longer
        guarantees.  Caller holds the cache lock (same contract as
        sync())."""
        self._dev = None
        self._synced_gen = 0
        self._struct_gen = 0
        self._shape = None
        self._epoch = None
        self._buffer_id = 0
        self._inval_gen += 1

    def _full_upload(self, host: schema.ClusterTensors) -> schema.ClusterTensors:
        # host-copy before device_put: on the CPU backend device_put can
        # zero-copy a numpy view, which would alias live cache state
        # (see TPUBatchScheduler.encode_pending's aliasing note)
        self.resync_total += 1
        self._buffer_id = epochs.fresh_buffer_id()
        copied = jax.tree.map(np.array, host)
        if self._shardings is None:
            return jax.device_put(copied)
        # mesh: the upload lands already sharded over the node axis;
        # buckets smaller than the mesh replicate instead (they solve
        # single-chip anyway — TPUBatchScheduler._sharded_ok)
        self._resident_sharded = (
            copied.allocatable.shape[0] % self.mesh.devices.size == 0
        )
        return jax.device_put(
            copied,
            self._shardings if self._resident_sharded
            else self._rep_shardings,
        )

    def _apply_deltas(
        self,
        host: schema.ClusterTensors,
        static_idx: np.ndarray,
        usage_idx: np.ndarray,
    ) -> schema.ClusterTensors:
        dev = self._dev
        self.delta_syncs += 1
        self.delta_rows_total += int(static_idx.shape[0] + usage_idx.shape[0])
        if self._shardings is not None and not self._resident_sharded:
            # replicated resident copy (bucket smaller than the mesh):
            # the pinned-sharding scatters don't apply — use the plain
            # ones; operands are all mesh-replicated so placement agrees
            set_rows, set_ax1 = _set_rows, _set_rows_ax1
        else:
            set_rows, set_ax1 = self._set, self._set_ax1
        updates = {}
        if static_idx.shape[0]:
            bucket = vb.pad_dim(static_idx.shape[0], 1)
            pidx = _pad_idx(static_idx, bucket)
            idx_dev = self._put_small(pidx)
            for leaf in _STATIC_LEAVES:
                vals = self._put_small(np.asarray(getattr(host, leaf))[pidx])
                updates[leaf] = set_rows(getattr(dev, leaf), idx_dev, vals)
            tvals = self._put_small(np.asarray(host.taint_bits)[:, pidx])
            updates["taint_bits"] = set_ax1(
                dev.taint_bits, idx_dev, tvals
            )
        if usage_idx.shape[0]:
            bucket = vb.pad_dim(usage_idx.shape[0], 1)
            pidx = _pad_idx(usage_idx, bucket)
            idx_dev = self._put_small(pidx)
            base = dev._replace(**updates) if updates else dev
            for leaf in _USAGE_LEAVES:
                vals = self._put_small(np.asarray(getattr(host, leaf))[pidx])
                updates[leaf] = set_rows(getattr(base, leaf), idx_dev, vals)
        return dev._replace(**updates) if updates else dev
